#!/usr/bin/env python
"""Pre-bake the chip-session sweep programs into the persistent cache.

Compile cost is the dominant tax on fresh sweep shapes (~150 s per
program shape for BDF, ~400 s for SDIRK at GRI scale — PERF.md), and
on-chip windows are SIGTERM-bounded: a window that compiles is a window
that doesn't measure.  This CLI resolves the lane counts you intend to
sweep onto their canonical buckets (batchreactor_tpu/aot), compiles ONE
program per bucket through the real sweep drivers, and persists the
executables in JAX's compilation cache with an on-disk manifest — so the
session's sweeps (at ANY lane count inside the warmed buckets) start
solving immediately.  Run it on the same platform the session will use:
executables are backend-specific.

  # warm the pow2 buckets covering 48..512 lanes of a GRI ignition sweep
  python scripts/warm_cache.py --mech tests/fixtures/grimech.dat \\
      --therm tests/fixtures/therm.dat --comp CH4=0.25,O2=0.5,N2=0.25 \\
      --T 1500 --lanes 48,200,512 --segment-steps 256 --ignition-marker CH4

  # inspect the manifest (no compiles, no device)
  python scripts/warm_cache.py --cache-dir .jax_cache --list

  # warm a SERVING session's program set from its spec file — the same
  # serve.json scripts/serve.py loads, so the warmer and the daemon
  # provably share one bucket-ladder/solver-config fingerprint
  python scripts/warm_cache.py --spec serve.json

  # coverage check: flag manifest entries the serving spec expects but
  # the cache is missing (or that went stale under a jax upgrade)
  python scripts/warm_cache.py --spec serve.json --list

Programs key on mechanism fingerprint x solver config x bucket x flag
set; the warmed flag set must MATCH the session's sweep call (method,
tolerances, jac_window, segment_steps, telemetry/stats, ignition
observer) — this CLI mirrors ``batch_reactor_sweep``'s construction
path exactly, so matching the CLI flags to the sweep kwargs suffices;
``--spec`` goes further and derives the flag set from the daemon's own
``SolverSession.warmup_specs()``, making drift structurally impossible.
Non-gas chemistry modes warm through the ``batchreactor_tpu.aot.warmup``
API directly.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _parse_comp(text):
    comp = {}
    for part in text.split(","):
        name, _, val = part.partition("=")
        comp[name.strip()] = float(val)
    return comp


def list_manifest(cache_dir):
    """Render the manifest without touching jax or a device: every
    entry with its (B, S, R) shape, staleness, NEVER-HIT status (zero
    persistent-cache hits — warmed but no session ever loaded it, the
    registry's eviction candidates), pin state, and the cache dir's
    total bytes on disk."""
    from batchreactor_tpu.aot import (cache_stats, load_manifest,
                                      manifest_path)

    man = load_manifest(cache_dir)
    entries = man.get("entries", {})
    stats = cache_stats(cache_dir)
    print(f"manifest {manifest_path(cache_dir)} "
          f"(jax {man.get('jax', '?')}, package {man.get('package', '?')}):"
          f" {len(entries)} programs")
    cur_jax = man.get("jax")
    never_hit = set(stats["never_hit"])
    stale = 0
    for key in sorted(entries):
        e = entries[key]
        tag = ""
        if cur_jax is not None and e.get("jax") != cur_jax:
            tag = f"  [STALE: warmed under jax {e.get('jax')}]"
            stale += 1
        if key in never_hit:
            tag += "  [NEVER-HIT]"
        if e.get("pinned"):
            tag += "  [PINNED]"
        shape = (f" s={e['s_bucket']} r={e['r_bucket']}"
                 if "s_bucket" in e else "")
        # static cost-model columns (banked by aot.warmup from
        # analysis/costmodel.py estimate_rung, ~3x band); "-" for
        # entries warmed before the model landed — this listing must
        # stay runnable with no jax, so never recompute here
        est_hbm = e.get("est_hbm_bytes")
        est = (f" pred_hbm="
               + (f"{est_hbm / 2**20:.1f}MiB" if est_hbm >= 2**20
                  else f"{est_hbm / 1024:.0f}KiB")
               + f" pred_flops/step={e['est_flops_per_step']:.3g}"
               if est_hbm is not None else " pred_hbm=- pred_flops/step=-")
        print(f"  {key}: bucket={e['bucket']}{shape}{est} "
              f"warmups={e['warmups']} "
              f"compiles={e['compiles']} ({e['compile_s']:.1f}s) "
              f"hits={e['cache_hits']} misses={e['cache_misses']} "
              f"last={e.get('last_used', e.get('last_warmed', '?'))}"
              f"{tag}")
    if stale:
        print(f"  {stale} stale entr{'y' if stale == 1 else 'ies'} — "
              f"re-run warmup under the current jax")
    if never_hit:
        print(f"  {len(never_hit)} never-hit entr"
              f"{'y' if len(never_hit) == 1 else 'ies'} — warmed but "
              f"never loaded by any session (eviction candidates)")
    print(f"  cache dir: {stats['cache_files']} files, "
          f"{stats['total_cache_bytes'] / 1e6:.1f} MB")
    return 0


def fanout_warm(args):
    """``--fanout N --spec serve.json``: per-host AOT warmup fanout
    (ROADMAP 2) — N worker PROCESSES warm disjoint round-robin shards
    of the session's warmup specs concurrently against ONE shared
    persistent cache (jax's cache writes are per-file atomic, so
    concurrent writers compose), each recording its counters in a
    private part manifest; the parent then folds the parts into the
    main manifest crash-atomically (aot.merge_manifests: tmp +
    os.replace, the PR-7 chunk convention — a SIGKILL at any point
    loses no counters and never tears the manifest)."""
    import subprocess

    n = int(args.fanout)
    cmd_base = [sys.executable, os.path.abspath(__file__),
                "--spec", args.spec, "--cache-dir", args.cache_dir]
    procs = []
    tags = []
    for i in range(n):
        tag = f"fanout-{os.getpid()}-{i}"
        tags.append(tag)
        procs.append(subprocess.Popen(
            cmd_base + ["--fanout-worker", f"{i}:{n}",
                        "--manifest-tag", tag],
            stdout=subprocess.PIPE, stderr=sys.stderr))
    outs, rcs = [], []
    for p in procs:
        out, _ = p.communicate()
        rcs.append(p.returncode)
        try:
            outs.append(json.loads(out.decode() or "{}"))
        except ValueError:
            outs.append({})
    from batchreactor_tpu.aot import load_manifest, merge_manifests

    merge_manifests(args.cache_dir, tags)
    man = load_manifest(args.cache_dir)
    summary = {
        "workers": n,
        "worker_rcs": rcs,
        "programs": sum(o.get("programs", 0) for o in outs),
        "already_warm": sum(o.get("already_warm", 0) for o in outs),
        "compiled": sum(o.get("compiled", 0) for o in outs),
        "compile_s": round(sum(o.get("compile_s", 0.0) for o in outs), 3),
        "manifest_entries": len(man.get("entries", {})),
        "cache_dir": os.path.abspath(args.cache_dir),
    }
    print(json.dumps(summary))
    return 0 if all(rc == 0 for rc in rcs) else 1


def warm_from_spec(args):
    """``--spec serve.json``: derive the warmup specs from the DAEMON'S
    own session object (serving.session.SolverSession.warmup_specs), so
    the warmed program keys are the served program keys by
    construction.  With ``--list``, no compiles: the expected keys
    (aot.spec_keys — same derivation, no execution) are checked against
    the manifest and missing/stale entries flagged."""
    # the cache dir must be pinned BEFORE jax compiles anything
    from batchreactor_tpu import aot

    aot.configure_cache(args.cache_dir)
    from batchreactor_tpu.serving.session import SolverSession

    session = SolverSession.from_spec(args.spec)
    specs = session.warmup_specs()
    if args.fanout_worker:
        # one fanout shard (fanout_warm spawns these): round-robin by
        # spec index, so shard unions cover the spec list exactly
        idx, total = (int(v) for v in args.fanout_worker.split(":"))
        specs = [s for k, s in enumerate(specs) if k % total == idx]
        if not specs:
            print(json.dumps({"programs": 0, "already_warm": 0,
                              "compiled": 0, "compile_s": 0.0,
                              "keys": []}))
            return 0
        results = aot.warmup(specs, cache_dir=args.cache_dir,
                             log=lambda m: print(m, file=sys.stderr),
                             manifest_tag=args.manifest_tag)
        warm = sum(r.warm for r in results)
        print(json.dumps({
            "programs": len(results),
            "already_warm": warm,
            "compiled": len(results) - warm,
            "compile_s": round(sum(r.compile_s for r in results), 3),
            "keys": [r.key for r in results],
        }))
        return 0
    if args.list:
        man = aot.load_manifest(args.cache_dir)
        entries = man.get("entries", {})
        cur_jax = man.get("jax")
        missing = stale = 0
        print(f"spec {args.spec}: fingerprint "
              f"{session.fingerprint[:12]}..., "
              f"{len(specs)} rungs (cap {session.bucket_cap})")
        for spec in specs:
            for key, bucket in aot.spec_keys(spec):
                e = entries.get(key)
                if e is None:
                    print(f"  {key}: bucket={bucket}  [MISSING: the "
                          f"daemon would compile this]")
                    missing += 1
                elif cur_jax is not None and e.get("jax") != cur_jax:
                    print(f"  {key}: bucket={bucket}  [STALE: warmed "
                          f"under jax {e.get('jax')}]")
                    stale += 1
                else:
                    print(f"  {key}: bucket={bucket}  warm "
                          f"(compiles={e['compiles']}, "
                          f"hits={e['cache_hits']})")
        if missing or stale:
            print(f"  {missing} missing / {stale} stale — run "
                  f"warm_cache.py --spec {args.spec} (no --list)")
            return 1
        print("  cache covers the spec")
        return 0
    import jax

    print(f"warming serving spec {args.spec} "
          f"({len(specs)} rungs, cap {session.bucket_cap}) on "
          f"{jax.default_backend()} (cache: {args.cache_dir})",
          file=sys.stderr)
    results = session.warmup(cache_dir=args.cache_dir,
                             log=lambda m: print(m, file=sys.stderr))
    warm = sum(r.warm for r in results)
    print(json.dumps({
        "programs": len(results),
        "already_warm": warm,
        "compiled": len(results) - warm,
        "compile_s": round(sum(r.compile_s for r in results), 3),
        "fingerprint": session.fingerprint,
        "cache_dir": os.path.abspath(args.cache_dir),
        "keys": [r.key for r in results],
    }))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pre-compile canonical bucketed sweep programs into "
                    "the persistent compilation cache")
    ap.add_argument("--mech", help="CHEMKIN gas mechanism file")
    ap.add_argument("--therm", help="NASA-7 thermo database")
    ap.add_argument("--comp", default="CH4=0.25,O2=0.5,N2=0.25",
                    help="inlet mole fractions, SP=x comma-separated")
    ap.add_argument("--T", type=float, default=1500.0,
                    help="exemplar temperature [K] (only shapes matter)")
    ap.add_argument("--p", type=float, default=1e5, help="pressure [Pa]")
    ap.add_argument("--lanes", default="64,128,256,512",
                    help="lane counts the session will sweep")
    ap.add_argument("--buckets", default="pow2",
                    help="'pow2' or an explicit ladder like 64,256,1024")
    ap.add_argument("--method", default="bdf", choices=["bdf", "sdirk"])
    ap.add_argument("--rtol", type=float, default=1e-6)
    ap.add_argument("--atol", type=float, default=1e-10)
    ap.add_argument("--segment-steps", type=int, default=256,
                    help="segmented-driver launch bound; 0 warms the "
                         "monolithic program instead")
    ap.add_argument("--max-steps", type=int, default=200_000,
                    help="monolithic max_steps (static; part of the "
                         "program key) — segmented runs ignore it")
    ap.add_argument("--jac-window", default="auto",
                    help="'auto' (platform rule) or an int")
    ap.add_argument("--ignition-marker",
                    help="species name for the in-loop ignition observer")
    ap.add_argument("--ignition-mode", default="half",
                    choices=["half", "peak"])
    ap.add_argument("--stats", action="store_true",
                    help="warm the telemetry-instrumented (stats=True) "
                         "program variant, as telemetry=True sweeps run")
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           os.path.join(REPO, ".jax_cache")),
                    help="managed persistent-cache directory")
    ap.add_argument("--list", action="store_true",
                    help="print the cache manifest and exit (no compiles); "
                         "with --spec additionally flag entries the "
                         "session spec expects but the manifest lacks")
    ap.add_argument("--spec",
                    help="warm a serving session's program set from its "
                         "serve.json (serving.session.load_spec grammar) "
                         "— the daemon and the warmer then share one "
                         "fingerprint by construction")
    ap.add_argument("--fanout", type=int, default=0,
                    help="with --spec: warm the spec's program set with "
                         "this many concurrent worker processes against "
                         "the shared persistent cache (per-host pod-tier "
                         "warmup); part manifests merge crash-atomically")
    ap.add_argument("--fanout-worker", help=argparse.SUPPRESS)
    ap.add_argument("--manifest-tag", help=argparse.SUPPRESS)
    ap.add_argument("--evict", type=int, metavar="MAX_PROGRAMS",
                    help="LRU-evict unpinned manifest entries beyond "
                         "MAX_PROGRAMS (pinned entries never evict); "
                         "no compiles, no device")
    ap.add_argument("--pin", action="append", default=[], metavar="KEY",
                    help="pin manifest entries (exempt from --evict and "
                         "the serving store's LRU policy); repeatable")
    ap.add_argument("--unpin", action="append", default=[],
                    metavar="KEY", help="unpin manifest entries")
    args = ap.parse_args(argv)

    if args.evict is not None or args.pin or args.unpin:
        from batchreactor_tpu.aot import enforce_capacity, pin_keys

        out = {}
        if args.pin:
            out["pinned"] = pin_keys(args.cache_dir, args.pin, True)
        if args.unpin:
            out["unpinned"] = pin_keys(args.cache_dir, args.unpin, False)
        if args.evict is not None:
            out["evicted"] = enforce_capacity(args.cache_dir, args.evict)
        print(json.dumps(out))
        return 0
    if args.fanout and args.spec and not args.fanout_worker:
        return fanout_warm(args)
    if args.spec:
        return warm_from_spec(args)
    if args.list:
        return list_manifest(args.cache_dir)
    if not args.mech or not args.therm:
        ap.error("--mech and --therm are required (or use --list/--spec)")

    # the cache dir must be pinned BEFORE jax compiles anything
    from batchreactor_tpu import aot

    aot.configure_cache(args.cache_dir)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.api import _sweep_fns, resolve_jac_window
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors

    gm = br.compile_gaschemistry(args.mech)
    th = br.create_thermo(list(gm.species), args.therm)
    sp = list(gm.species)
    comp = _parse_comp(args.comp)
    idx = {s.upper(): k for k, s in enumerate(sp)}
    X = np.zeros((1, len(sp)))
    for name, val in comp.items():
        if name.upper() not in idx:
            ap.error(f"composition species {name!r} not in mechanism")
        X[0, idx[name.upper()]] = val
    marker_idx = None
    if args.ignition_marker:
        if args.ignition_marker.upper() not in idx:
            ap.error(f"ignition marker {args.ignition_marker!r} not in "
                     f"mechanism")
        marker_idx = idx[args.ignition_marker.upper()]

    # the EXACT callables batch_reactor_sweep builds (api._sweep_fns):
    # identical construction => identical traced program => identical
    # persistent-cache key in the later session process
    rhs, jac, observer, obs0 = _sweep_fns(
        "gas", None, gm, None, th, False, True, marker_idx,
        args.ignition_mode)
    T = jnp.asarray([args.T], dtype=jnp.float64)
    y0 = sweep_solution_vectors(jnp.asarray(X), th.molwt, T, args.p)[0]
    jw = (resolve_jac_window(None, args.method) if args.jac_window == "auto"
          else int(args.jac_window))
    lanes = [int(b) for b in args.lanes.split(",")]
    buckets = (args.buckets if args.buckets == "pow2"
               else tuple(int(b) for b in args.buckets.split(",")))
    spec = dict(rhs=rhs, y0=y0, cfg={"T": args.T, "Asv": 1.0},
                lanes=lanes, buckets=buckets, method=args.method,
                rtol=args.rtol, atol=args.atol, jac=jac,
                observer=observer, observer_init=obs0, jac_window=jw,
                stats=args.stats)
    if args.segment_steps > 0:
        spec["segment_steps"] = args.segment_steps
    else:
        spec["max_steps"] = args.max_steps

    print(f"warming {len(lanes)} lane counts -> buckets "
          f"{aot.bucket_ladder(lanes, buckets)} on "
          f"{jax.default_backend()} (cache: {args.cache_dir})",
          file=sys.stderr)
    results = aot.warmup([spec], cache_dir=args.cache_dir,
                         log=lambda m: print(m, file=sys.stderr))
    total_compile = sum(r.compile_s for r in results)
    warm = sum(r.warm for r in results)
    print(json.dumps({
        "programs": len(results),
        "already_warm": warm,
        "compiled": len(results) - warm,
        "compile_s": round(total_compile, 3),
        "cache_dir": os.path.abspath(args.cache_dir),
        "keys": [r.key for r in results],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
