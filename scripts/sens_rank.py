#!/usr/bin/env python
"""Top-k reaction sensitivity ranking for a batch-reactor input file.

The CLI face of the sensitivity subsystem (docs/sensitivity.md): solve
the run described by a reference-format ``batch.xml``, differentiate a
scalar QoI with respect to the selected mechanism parameters, and print
the normalized coefficients d ln(QoI)/d ln(A_i) ranked by magnitude.

  python scripts/sens_rank.py INPUT.xml LIB_DIR --qoi H2O
  python scripts/sens_rank.py INPUT.xml LIB_DIR --qoi ignition:OH \\
      --mode adjoint -k 15
  python scripts/sens_rank.py INPUT.xml LIB_DIR --qoi H2O \\
      --reactions '*H2O2*' --surf

``--mode adjoint`` (default) costs one backward pass regardless of how
many reactions are ranked; ``--mode forward`` propagates one tangent row
per parameter (exact same answers, linear-in-P cost) — see the decision
table in docs/sensitivity.md.
"""

import argparse
import os
import sys

# runnable from a source checkout without an install, like scripts/brlint.py
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_parser():
    p = argparse.ArgumentParser(
        prog="sens_rank",
        description="rank reactions by normalized QoI sensitivity "
                    "(d ln QoI / d ln A)")
    p.add_argument("input_xml", help="reference-format batch.xml")
    p.add_argument("lib_dir", help="mechanism library directory")
    p.add_argument("--qoi", required=True,
                   help="species name (final mass-density QoI) or "
                        "'ignition:MARKER[:FRAC]' (adjoint only)")
    p.add_argument("--mode", choices=("adjoint", "forward"),
                   default="adjoint")
    p.add_argument("--gas", action="store_true", default=True,
                   help="gas-phase chemistry (default)")
    p.add_argument("--no-gas", dest="gas", action="store_false")
    p.add_argument("--surf", action="store_true",
                   help="surface chemistry (combine with --gas for "
                        "coupled)")
    p.add_argument("--fields", default="log_A",
                   help="comma-separated theta fields (default log_A; "
                        "ranking normalizes log_A only)")
    p.add_argument("--reactions", default=None,
                   help="reaction selection glob (default: all)")
    p.add_argument("-k", type=int, default=10, help="rows to print")
    p.add_argument("--rtol", type=float, default=1e-6)
    p.add_argument("--atol", type=float, default=1e-10)
    p.add_argument("--sens-grid", type=int, default=512,
                   help="adjoint fixed re-solve grid size")
    return p


def main(argv=None):
    args = _build_parser().parse_args(argv)

    # host-first import discipline (scripts/brlint.py): pin CPU unless the
    # operator asked for an accelerator — ranking a fixture mechanism must
    # not hang on a wedged tunneled TPU
    os.environ.setdefault("BR_PLATFORM", os.environ.get("JAX_PLATFORMS",
                                                        "cpu"))
    import batchreactor_tpu as br
    from batchreactor_tpu.sensitivity import rank

    qoi = args.qoi
    if qoi.lower().startswith("ignition:"):
        parts = qoi.split(":")
        qoi = ("ignition", parts[1]) if len(parts) == 2 else (
            "ignition", parts[1], float(parts[2]))
    fields = tuple(f.strip() for f in args.fields.split(",") if f.strip())
    sens_params = {"fields": fields}
    if args.reactions is not None:
        sens_params["reactions"] = args.reactions

    sol = br.batch_reactor(
        args.input_xml, args.lib_dir, gaschem=args.gas,
        surfchem=args.surf, sens=args.mode, sens_qoi=qoi,
        sens_params=sens_params, sens_grid=args.sens_grid,
        rtol=args.rtol, atol=args.atol, verbose=False)
    if sol.status != "Success":
        print(f"sens_rank: solve ended with {sol.status}", file=sys.stderr)
        return 1
    if getattr(sol, "truncated", False):
        print("sens_rank: adjoint grid overflowed — the ranking below is "
              "for a shortened horizon; re-run with a larger --sens-grid",
              file=sys.stderr)
        return 1
    if sol.qoi_grad is None or "log_A" not in sol.qoi_grad:
        print("sens_rank: no log_A gradient to rank (include log_A in "
              "--fields)", file=sys.stderr)
        return 2
    coeffs = rank.normalized_sensitivities(sol.qoi, sol.qoi_grad["log_A"])
    qoi_name = args.qoi if isinstance(args.qoi, str) else "tau_ign"
    print(f"QoI = {float(sol.qoi):.6e}  "
          f"({sol.spec.n_reactions} reactions ranked, mode={args.mode})")
    print(rank.format_ranking(rank.top_k(coeffs, sol.spec.equations,
                                         k=args.k), qoi_name=qoi_name))
    return 0


if __name__ == "__main__":
    sys.exit(main())
