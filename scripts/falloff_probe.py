"""Reconstruct the reference RHS at t=0 from the golden trajectory and rank
falloff-convention candidates against every active species at once.

Golden: /root/reference/test/batch_gas_and_surf/gas_profile.csv rows 1-2
(dt = 4.32e-16 s -> finite difference measures the RHS at the initial state
to ~1e-4 relative).  Known-good conventions (PARITY.md): forward rates,
third-body, kc_compat reverse for non-falloff.  Unknown: falloff fwd/rev.
"""
import sys
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
import batchreactor_tpu as br
from batchreactor_tpu.ops import gas_kinetics as gk
from batchreactor_tpu.ops.thermo import gibbs_over_RT
from batchreactor_tpu.utils.constants import R

LIB = "/root/reference/test/lib"
CSV = "/root/reference/test/batch_gas_and_surf/gas_profile.csv"

gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
sp = list(gm.species)
S = len(sp)

rows = np.loadtxt(CSV, delimiter=",", skiprows=1, max_rows=3)
hdr = open(CSV).readline().strip().split(",")
assert hdr[4:] == [s if s != "CH2(S)" else "CH2(S)" for s in sp], "species order"
T = rows[0, 1]
molwt = np.asarray(th.molwt)

def row_to_rhok(r):
    x = r[4:]
    rho = r[3]
    wbar = (x * molwt).sum()
    Y = x * molwt / wbar
    return rho * Y

r0, r1 = row_to_rhok(rows[0]), row_to_rhok(rows[1])
dt = rows[1, 0] - rows[0, 0]
rhs_gold = (r1 - r0) / dt  # kg/m^3/s per species; includes surface terms!

# surface contribution at t=0 (conventions confirmed <0.1%): subtract it
from batchreactor_tpu.ops import surface_kinetics
from batchreactor_tpu.models.surface import compile_mech
sm = compile_mech(f"{LIB}/ch4ni.xml", th, sp)
x0 = rows[0, 4:]
p0 = rows[0, 2]
theta0 = np.asarray(sm.ini_covg)
sg, ss = surface_kinetics.production_rates(T, p0, jnp.asarray(x0),
                                           jnp.asarray(theta0), sm)
rhs_surf = np.asarray(sg) * molwt  # Asv=1
rhs_gas_gold = rhs_gold - rhs_surf

conc = jnp.asarray(r0 / molwt)  # mol/m^3

# --- candidate machinery ------------------------------------------------
kinf = np.asarray(gk._arrhenius(T, gm.log_A, gm.beta, gm.Ea))
k0 = np.asarray(gk._arrhenius(T, gm.log_A0, gm.beta0, gm.Ea0))
cM = np.asarray(gm.eff @ conc)
has_fall = np.asarray(gm.has_falloff) > 0
ratio = k0 / np.maximum(kinf, 1e-300)
Pr = ratio * np.maximum(cM, 0.0)
L = Pr / (1 + Pr)
F = np.asarray(gk._troe_F(jnp.asarray(T), jnp.asarray(Pr), gm.troe, gm.has_troe))
g = np.asarray(gibbs_over_RT(T, th))
dnu = np.asarray(gm.nu_r - gm.nu_f)
dG = dnu @ g
dn = dnu.sum(axis=1)
nu_f = np.asarray(gm.nu_f); nu_r = np.asarray(gm.nu_r)
tb = np.where(np.asarray(gm.has_tb) > 0, cM, 1.0)
rev = np.asarray(gm.rev_mask) > 0
concn = np.asarray(conc)

def production(kf_fall, Kc_fall_log):
    """omega_dot given falloff fwd rate constants + falloff ln Kc."""
    kf = np.where(has_fall, kf_fall, kinf)
    # non-falloff ln Kc: kc_compat quirk (confirmed)
    log_c0 = np.log(1e5 / (R * T)) + np.log(1e6)
    lKc = -dG + dn * log_c0
    lKc = np.where(has_fall, Kc_fall_log, lKc)
    kr = np.where(rev, kf * np.exp(-np.clip(lKc, -690, 690) * 1.0) ** 1.0, 0.0)
    kr = np.where(rev, kf * np.exp(np.clip(-lKc, -690, 690)), 0.0)
    def powprod(nu):
        with np.errstate(divide="ignore"):
            lp = nu @ np.log(np.maximum(concn, 1e-300))
        return np.exp(lp)
    q = tb * (kf * powprod(nu_f) - kr * powprod(nu_r))
    return dnu.T @ q

# candidate falloff fwd constants
c0_si = 101325.0 / (R * T)
cand_kf = {
    "phys(kinf*L*F)": kinf * L * F,
    "kinf": kinf,
    "kinf*F": kinf * F,
    "kinf*L": kinf * L,
    "k0": k0,
    "k0*cM": k0 * cM,
    "k0*cM*L*F": k0 * cM * L * F,
    "kinf*cM": kinf * cM,
    "kinf*cM*L*F": kinf * cM * L * F,
    "kinf/(1+Pr)*F": kinf / (1 + Pr) * F,
    "lindemann(noF)": kinf * L,
}
# candidate falloff ln Kc
log_c0_atm = np.log(101325.0 / (R * T))
log_c0_bar = np.log(1e5 / (R * T))
cand_kc = {
    "phys(atm)": -dG + dn * log_c0_atm,
    "bar": -dG + dn * log_c0_bar,
    "quirk(bar*1e6)": -dG + dn * (log_c0_bar + np.log(1e6)),
    "Kp": -dG,
    "inv_quirk(bar/1e6)": -dG + dn * (log_c0_bar - np.log(1e6)),
}

mask_active = np.abs(rhs_gas_gold) > 1e-25
print("species with nonzero golden gas RHS:",
      [sp[i] for i in np.nonzero(mask_active)[0]])

results = []
for nk, kf_fall in cand_kf.items():
    for nc, kc_fall in cand_kc.items():
        w = production(kf_fall, kc_fall)
        ours = w * molwt
        # relative error on active species
        rel = np.abs(ours[mask_active] - rhs_gas_gold[mask_active]) / np.abs(
            rhs_gas_gold[mask_active])
        results.append((float(np.max(rel)), float(np.median(rel)), nk, nc))
results.sort()
print(f"{'max_rel':>10} {'med_rel':>10}  kf_falloff / Kc_falloff")
for mx, med, nk, nc in results[:15]:
    print(f"{mx:10.3e} {med:10.3e}  {nk} / {nc}")

# detailed per-species for the best
mx, med, nk, nc = results[0]
w = production(cand_kf[nk], cand_kc[nc])
ours = w * molwt
print(f"\nbest: {nk} / {nc}")
for i in np.nonzero(mask_active)[0]:
    print(f"  {sp[i]:>8}: gold {rhs_gas_gold[i]: .4e}  ours {ours[i]: .4e} "
          f" ratio {ours[i]/rhs_gas_gold[i]: .4f}")
