"""Wedge-safe runner for the on-chip pytest smoke tier (``-m tpu``).

Launches ``pytest tests -m tpu`` in a child process with ``BR_TEST_TPU=1``
(tests/conftest.py then leaves the real accelerator backend in place) and a
SIGTERM-first timeout: a SIGKILLed TPU client wedges the tunneled chip for
hours (PERF.md round-2/3 postmortems), so the child gets SIGTERM plus a
45 s grace period before any KILL, and the runner itself never touches the
device.  Writes TPU_SMOKE.json (override with TPU_SMOKE_OUT) recording
pass/fail counts, duration, and the tail of the pytest output — the
per-round artifact the round-3 verdict asked for (chip regressions caught
by tests, not only bench).

Usage:
  python scripts/tpu_smoke.py                      # full tier, 2400 s cap
  TPU_SMOKE_TIMEOUT=900 TPU_SMOKE_K=file_driven python scripts/tpu_smoke.py
"""

import importlib.util
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the SIGTERM-with-grace rule lives in resilience/guard.py (stdlib-only);
# loaded from its file so this runner never imports jax
_spec = importlib.util.spec_from_file_location(
    "_br_resilience_guard",
    os.path.join(REPO, "batchreactor_tpu", "resilience", "guard.py"))
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)
run_guarded = _guard.run_guarded


def main():
    timeout = int(os.environ.get("TPU_SMOKE_TIMEOUT", "2400"))
    out_path = os.environ.get("TPU_SMOKE_OUT",
                              os.path.join(REPO, "TPU_SMOKE.json"))
    env = {**os.environ, "BR_TEST_TPU": "1",
           "JAX_COMPILATION_CACHE_DIR": os.path.join(REPO, ".jax_cache"),
           "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "2"}
    cmd = [sys.executable, "-m", "pytest", os.path.join(REPO, "tests"),
           "-m", "tpu", "-q", "--no-header", "-rA"]
    if os.environ.get("TPU_SMOKE_K"):
        cmd += ["-k", os.environ["TPU_SMOKE_K"]]

    r = run_guarded(cmd, timeout, env=env, cwd=REPO, merge_stderr=True)
    stdout = r.stdout

    counts = {}
    m = re.search(r"(\d+) passed", stdout or "")
    counts["passed"] = int(m.group(1)) if m else 0
    m = re.search(r"(\d+) failed", stdout or "")
    counts["failed"] = int(m.group(1)) if m else 0
    m = re.search(r"(\d+) skipped", stdout or "")
    counts["skipped"] = int(m.group(1)) if m else 0
    m = re.search(r"(\d+) error", stdout or "")
    counts["errors"] = int(m.group(1)) if m else 0

    rec = {
        "tier": "tpu-smoke (-m tpu)",
        "rc": r.rc,
        "timed_out": r.timed_out,
        "wall_s": round(r.wall_s, 1),
        "counts": counts,
        "ok": (not r.timed_out and r.rc == 0
               and counts["passed"] > 0 and counts["failed"] == 0),
        "output_tail": (stdout or "")[-3000:],
    }
    with open(out_path, "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps({k: v for k, v in rec.items() if k != "output_tail"}))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
