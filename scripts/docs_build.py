"""Docs build + doc-example check — the CI analog of the reference's
Documenter.jl build-and-doctest job (/root/reference/.github/workflows/
CI.yml:42-59, /root/reference/docs/make.jl:1-26).

Renders every ``docs/*.md`` page to ``docs/_site/*.html`` (via the
``markdown`` package when available, with a dependency-free fallback
renderer good enough for a link-able artifact) and checks the doc examples
the way doctests would:

- every fenced ``python`` block must *compile* (syntax drift fails CI);
- every ``import``/``from ... import`` inside those blocks must resolve
  against the installed package, and attribute references on the
  conventional aliases (``br.`` / module aliases from the imports) must
  exist — so a renamed or removed API symbol breaks the docs job even
  though the examples use placeholder file paths and cannot execute
  end-to-end.

Usage: python scripts/docs_build.py [--check]   (--check = no site write)
"""

import ast
import html
import importlib
import pathlib
import re
import sys

# pin the CPU backend BEFORE the package import chain can initialize a
# device: the axon TPU plugin ignores the JAX_PLATFORMS env var, and a
# wedged tunnel turns any backend-touching import into a hang (round-1
# failure mode, tests/conftest.py) — the docs check is host-only work
import jax

jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parent.parent
DOCS = REPO / "docs"
SITE = DOCS / "_site"


def _render(md_text: str) -> str:
    try:
        import markdown

        body = markdown.markdown(md_text,
                                 extensions=["tables", "fenced_code"])
    except ImportError:
        # minimal fallback: headings, fences and paragraphs — enough to
        # produce a readable artifact without any dependency
        out, in_code = [], False
        for line in md_text.splitlines():
            if line.startswith("```"):
                out.append("</pre>" if in_code else "<pre>")
                in_code = not in_code
            elif in_code:
                out.append(html.escape(line))
            elif line.startswith("#"):
                n = len(line) - len(line.lstrip("#"))
                out.append(f"<h{n}>{html.escape(line[n:].strip())}</h{n}>")
            else:
                out.append(html.escape(line) + "<br/>")
        body = "\n".join(out)
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            "<title>batchreactor-tpu docs</title></head><body>"
            f"{body}</body></html>")


def _python_blocks(md_text: str):
    return re.findall(r"```python\n(.*?)```", md_text, flags=re.S)


_ALIAS_ROOTS = {"br": "batchreactor_tpu"}


def _check_block(src: str, where: str) -> list:
    errors = []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{where}: syntax error in doc example: {e}"]
    aliases = dict(_ALIAS_ROOTS)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                try:
                    importlib.import_module(a.name)
                except ImportError as e:
                    errors.append(f"{where}: import {a.name}: {e}")
                else:
                    aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[0] in ("batchreactor_tpu", "jax",
                                             "numpy"):
                try:
                    mod = importlib.import_module(node.module)
                except ImportError as e:
                    errors.append(f"{where}: from {node.module}: {e}")
                    continue
                for a in node.names:
                    if not hasattr(mod, a.name):
                        errors.append(f"{where}: {node.module} has no "
                                      f"symbol {a.name!r} (docs drift)")
    # attribute references on known aliases: br.batch_reactor, br.Chemistry...
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in aliases):
            mod = importlib.import_module(aliases[node.value.id])
            if not hasattr(mod, node.attr):
                errors.append(f"{where}: {aliases[node.value.id]} has no "
                              f"attribute {node.attr!r} (docs drift)")
    return errors


def main(argv):
    check_only = "--check" in argv
    pages = sorted(DOCS.glob("*.md"))
    if not pages:
        print("no docs/*.md pages found", file=sys.stderr)
        return 1
    errors = []
    if not check_only:
        SITE.mkdir(exist_ok=True)
    for page in pages:
        text = page.read_text()
        for i, block in enumerate(_python_blocks(text)):
            errors.extend(_check_block(block, f"{page.name}#block{i}"))
        html_text = _render(text)  # rendering itself is part of the check
        if check_only:
            print(f"checked {page.name} ({len(html_text)} bytes rendered, "
                  f"not written)")
        else:
            out = SITE / (page.stem + ".html")
            out.write_text(html_text)
            print(f"rendered {page.name} -> {out.relative_to(REPO)} "
                  f"({out.stat().st_size} bytes)")
    if errors:
        print("\nDOC CHECK FAILURES:", file=sys.stderr)
        for e in errors:
            print(" -", e, file=sys.stderr)
        return 1
    print(f"doc check ok: {len(pages)} page(s), all python blocks compile "
          f"and resolve against the installed package")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
