#!/usr/bin/env python
"""The resident solver daemon: sweep-as-a-service (docs/serving.md).

Loads a session spec (``serve.json`` — the SAME file
``scripts/warm_cache.py --spec`` pre-bakes programs for), warms the AOT
program set, and serves a live request stream from one warm,
continuously-batched device program:

  # HTTP daemon on an ephemeral port (the bound port prints as JSON)
  python scripts/serve.py --spec serve.json

  # fixed port, skip in-process warmup (a warmed persistent cache
  # makes the first request cheap anyway)
  python scripts/serve.py --spec serve.json --port 8371 --no-warmup

  # stdin-JSONL mode: one request per line in, one response per line
  # out (out-of-order; correlate by id).  Drain contract is EOF (close
  # stdin); SIGTERM keeps its default disposition here, dumping the
  # flight ring before terminating
  python scripts/serve.py --spec serve.json --jsonl < requests.jsonl

Endpoints: ``POST /solve`` (schema: docs/serving.md), ``GET /healthz``,
``GET /metrics`` (the PR-9 live plane — ``br_sweep_occupancy`` and the
``serve_*`` queue gauges move between mid-flight scrapes).  In HTTP
mode SIGTERM (or SIGINT) drains: in-flight and queued requests are
answered, new ones are rejected with ``draining``, the flight recorder
dumps a ``flight_*.jsonl`` postmortem, and the process exits 0 — run
it under ``resilience.run_guarded`` (SIGTERM-with-grace) like every
supervised driver in this repo.  In JSONL mode the drain trigger is
EOF (the parent owns stdin); SIGTERM terminates with a flight dump.
"""

import argparse
import json
import os
import signal
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", required=True,
                    help="session spec JSON (serve.json — shared with "
                         "warm_cache.py --spec)")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral; the bound port is "
                         "printed in the startup JSON line)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--jsonl", action="store_true",
                    help="stdin-JSONL mode instead of HTTP")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the in-process AOT warmup pass")
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR"),
                    help="persistent compilation cache directory")
    ap.add_argument("--flight-dir", default=".",
                    help="directory for flight_*.jsonl postmortem dumps")
    ap.add_argument("--obs-out",
                    help="write the session obs report JSONL here at "
                         "drain: spans, serve_stage_seconds histograms, "
                         "and per-request request_trace events — the "
                         "scripts/obs_trace.py waterfall and "
                         "scripts/obs_gate.py input (the CI serve-smoke "
                         "/ latency-gate artifact)")
    ap.add_argument("--store", action="store_true",
                    help="enable the multi-mechanism session store: "
                         "POST /mechanism uploads + per-request 'mech' "
                         "routing (docs/serving.md); the --spec "
                         "mechanism is the pinned default")
    ap.add_argument("--add-mech", action="append", default=[],
                    metavar="ID=MECH:THERM",
                    help="pre-admit extra mechanisms into the store "
                         "(implies --store); repeatable")
    ap.add_argument("--fleet-dir",
                    help="join the replicated serving tier (docs/"
                         "serving.md \"Fleet\"): register in this "
                         "shared fleet dir, heartbeat + metrics "
                         "snapshot while alive, drain-handshake on "
                         "teardown; warmup folds a per-member part "
                         "manifest into the shared --cache-dir")
    ap.add_argument("--member-name",
                    help="fleet member name (default m<pid>); only "
                         "meaningful with --fleet-dir")
    args = ap.parse_args(argv)
    if args.member_name and not args.fleet_dir:
        ap.error("--member-name needs --fleet-dir")
    if args.fleet_dir and args.jsonl:
        ap.error("--fleet-dir is HTTP-mode only (the router forwards "
                 "over HTTP)")
    member_name = (args.member_name or f"m{os.getpid()}"
                   if args.fleet_dir else None)

    # the cache dir must be pinned BEFORE jax compiles anything
    from batchreactor_tpu import aot

    if args.cache_dir:
        aot.configure_cache(args.cache_dir)

    from batchreactor_tpu.obs.live import arm_flight, flight_dump
    from batchreactor_tpu.serving.scheduler import Scheduler
    from batchreactor_tpu.serving.server import ServingServer, serve_jsonl
    from batchreactor_tpu.serving.session import SolverSession

    session = SolverSession.from_spec(args.spec)
    if not args.no_warmup:
        # fleet members warm one shared cache dir concurrently: each
        # writes a per-member part manifest and folds it crash-atomically
        # (aot.merge_manifests) instead of racing on the main manifest
        session.warmup(cache_dir=args.cache_dir,
                       log=lambda m: print(m, file=sys.stderr),
                       manifest_tag=member_name)
    scheduler = Scheduler(session)
    store = None
    if args.store or args.add_mech:
        from batchreactor_tpu.serving.session import SessionStore

        store = SessionStore(session, scheduler,
                             cache_dir=args.cache_dir)
        for spec_str in args.add_mech:
            mid, _, rest = spec_str.partition("=")
            mech, _, therm = rest.partition(":")
            if not (mid and mech and therm):
                ap.error(f"--add-mech wants ID=MECH:THERM, got "
                         f"{spec_str!r}")
            fp = store.add_mechanism(mech, therm, mech_id=mid,
                                     warm=not args.no_warmup)
            print(f"[serve] mechanism {mid!r} resident "
                  f"({fp[:12]}...)", file=sys.stderr)

    # HTTP mode drains on SIGTERM/SIGINT: OUR handler goes in first,
    # then arm_flight wraps it — the SIGTERM path therefore dumps the
    # flight ring and THEN chains into the drain trigger (the handler
    # only sets an event; the heavy teardown runs on the main thread).
    # JSONL mode's drain contract is EOF instead — the parent owns
    # stdin, and a blocked readline cannot observe an event — so the
    # signal dispositions stay default there (SIGTERM still dumps the
    # flight ring via arm_flight's handler before terminating).
    stop = threading.Event()

    def _on_term(_signum, _frame):
        stop.set()

    if not args.jsonl:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    arm_flight(recorder=session.recorder, dir=args.flight_dir,
               install_signal=True)

    def _write_obs():
        if not args.obs_out:
            return
        from batchreactor_tpu.obs import write_jsonl

        write_jsonl(args.obs_out, session.obs_report())
        print(f"[serve] obs report -> {args.obs_out}", file=sys.stderr)

    with session:
        if args.jsonl:
            scheduler.start()
            accepted, rejected = serve_jsonl(session, scheduler,
                                             sys.stdin, sys.stdout)
            _write_obs()
            print(json.dumps({"served": {"accepted": accepted,
                                         "rejected": rejected,
                                         "compiles": session
                                         .compile_summary()["compiles"]}}),
                  file=sys.stderr)
            return 0
        with ServingServer(session, scheduler, port=args.port,
                           host=args.host, store=store) as srv:
            if args.fleet_dir:
                # register only once the port is bound and the stream
                # is live — the router must never route to a member
                # that cannot answer; ServingServer.close runs the
                # drain handshake (mark_draining -> drain ->
                # deregister) on teardown
                from batchreactor_tpu.fleet import MemberRegistration

                srv.membership = MemberRegistration(
                    args.fleet_dir, member_name, srv.url,
                    pid=os.getpid(), registry=session.registry)
                srv.membership.register()
            print(json.dumps({"serving": {
                "url": srv.url, "port": srv.port, "pid": os.getpid(),
                "fingerprint": session.fingerprint,
                "bucket_cap": session.bucket_cap,
                "fleet": (None if not args.fleet_dir else
                          {"dir": args.fleet_dir, "member": member_name}),
                "store": (None if store is None else
                          [m["ids"] for m in store.mechanisms()]),
                "warmed": (None if session.warmed is None else
                           [r.key for r in session.warmed])}}),
                  flush=True)
            stop.wait()
            print("[serve] drain requested; answering in-flight work",
                  file=sys.stderr)
            # ServingServer.close drains the scheduler (every accepted
            # request answers) before stopping the HTTP thread
        flight_dump("serve-drain")
        _write_obs()
        w = session.compile_summary()
        print(json.dumps({"drained": {
            "compiles": w["compiles"], "retraces": w["retraces"]}}),
            file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
