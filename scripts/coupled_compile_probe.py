"""Coupled gas+surf TPU compile-wall localization ladder (round-4 task).

Round 3 found the coupled (GRI-3.0 + CH4/Ni) BDF program never finishes
compiling on the TPU backend (two attempts, 30 and 58 min) while the
same program compiles in ~10 s on CPU and the gas-only program compiles in
~150 s on the chip (PERF.md).  The one localization probe that existed ran
right after a killed TPU client, so a wedged tunnel could not be excluded.

This script is the clean re-localization: a LADDER of jits of increasing
completeness, each in its OWN subprocess with a SIGTERM-first timeout (a
SIGKILLed TPU client wedges the tunnel — round-2/3 postmortems), recording
per-stage compile+run seconds to COMPILE_PROBE.json.  Stages:

  s0_probe        tiny matmul — chip alive?
  s1_surf_rates   surface production_rates_and_jac, single lane
  s2_surf_jac     full coupled analytic Jacobian fn (make_surface_jac), B=64
  s3_rhs          coupled RHS vmapped, B=64
  s4_bdf_fwd      coupled BDF solve, jacfwd Jacobian, jw=1, tiny horizon
  s5_bdf_ana      coupled BDF solve, analytic Jacobian, jw=1
  s6_bdf_ana_jw8  coupled BDF solve, analytic Jacobian, jac_window=8
  s7_bdf_remat    like s5 but the Jacobian wrapped in jax.checkpoint

Any stage timing out marks where the compile pathology begins; later
stages still run (each is independent).  Usage:

  python scripts/coupled_compile_probe.py               # all stages, 600 s each
  CCP_STAGES=s2,s5 CCP_TIMEOUT=1200 python scripts/coupled_compile_probe.py
  CCP_B=16 ...                                          # smaller batch
"""

import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# THE SIGTERM-with-grace rule lives in resilience/guard.py (stdlib-only);
# loaded from its file so the parent ladder never imports jax
_spec = importlib.util.spec_from_file_location(
    "_br_resilience_guard",
    os.path.join(REPO, "batchreactor_tpu", "resilience", "guard.py"))
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)
run_guarded = _guard.run_guarded

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")

STAGES = ["s0_probe", "s1_surf_rates", "s2_surf_jac", "s3_rhs",
          "s4_bdf_fwd", "s5_bdf_ana", "s6_bdf_ana_jw8", "s7_bdf_remat"]


def _stage_main(stage):
    """Child body: build + jit + run ONE stage, print a json line."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    os.environ.setdefault("BR_EXP32", "1")
    import jax

    if os.environ.get("CCP_CPU") == "1":
        # control runs: the axon plugin ignores JAX_PLATFORMS, so the CPU
        # pin must go through jax.config before first backend use
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.models.surface import compile_mech
    from batchreactor_tpu.ops import surface_kinetics
    from batchreactor_tpu.ops.rhs import make_surface_jac, make_surface_rhs
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors
    from batchreactor_tpu.parallel.sweep import ensemble_solve

    B = int(os.environ.get("CCP_B", "64"))
    t_init = time.perf_counter()
    if stage == "s0_probe":
        x = jnp.ones((256, 256)) @ jnp.ones((256, 256))
        jax.block_until_ready(x)
        print(json.dumps({"stage": stage, "ok": True,
                          "backend": jax.default_backend(),
                          "wall_s": round(time.perf_counter() - t_init, 1)}))
        return

    gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sm = compile_mech(f"{LIB}/ch4ni.xml", th, list(gm.species))
    sp = list(gm.species)
    ng, ns = len(sp), len(sm.species)

    X = np.zeros(ng)
    X[sp.index("CH4")], X[sp.index("O2")], X[sp.index("N2")] = .25, .5, .25
    T_grid = jnp.linspace(1073.0, 1273.0, B)
    y0s = sweep_solution_vectors(jnp.broadcast_to(jnp.asarray(X), (B, ng)),
                                 th.molwt, T_grid, 1e5,
                                 ini_covg=sm.ini_covg)
    cfg = {"T": T_grid, "Asv": jnp.full((B,), 1.0)}
    build_s = time.perf_counter() - t_init

    rhs = make_surface_rhs(sm, th, gm=gm)
    jacf = make_surface_jac(sm, th, gm=gm)

    t0 = time.perf_counter()
    if stage == "s1_surf_rates":
        f = jax.jit(lambda T, p, x, th_: surface_kinetics.
                    production_rates_and_jac(T, p, x, th_, sm))
        out = f(1173.0, 1e5, jnp.asarray(X), sm.ini_covg)
        jax.block_until_ready(out)
    elif stage == "s2_surf_jac":
        f = jax.jit(jax.vmap(jacf, in_axes=(None, 0, {"T": 0, "Asv": 0})))
        out = f(0.0, y0s, cfg)
        jax.block_until_ready(out)
    elif stage == "s3_rhs":
        f = jax.jit(jax.vmap(rhs, in_axes=(None, 0, {"T": 0, "Asv": 0})))
        out = f(0.0, y0s, cfg)
        jax.block_until_ready(out)
    elif stage in ("s4_bdf_fwd", "s5_bdf_ana", "s6_bdf_ana_jw8",
                   "s7_bdf_remat"):
        import functools

        kw = dict(rtol=1e-6, atol=1e-10, method="bdf", max_steps=64)
        if stage == "s4_bdf_fwd":
            kw["jac"] = None
        elif stage == "s7_bdf_remat":
            kw["jac"] = jax.checkpoint(jacf)
        else:
            kw["jac"] = jacf
        kw["jac_window"] = 8 if stage == "s6_bdf_ana_jw8" else 1
        # tiny horizon + tiny step budget: the COMPILE is the measurement;
        # the program structure (while_loop body) is the full solver's
        res = ensemble_solve(rhs, y0s, 0.0, 1e-8, cfg, **kw)
        jax.block_until_ready(res.y)
    else:
        raise SystemExit(f"unknown stage {stage}")
    print(json.dumps({"stage": stage, "ok": True,
                      "backend": jax.default_backend(),
                      "build_s": round(build_s, 1),
                      "compile_and_run_s": round(time.perf_counter() - t0,
                                                 1)}))


def main():
    if os.environ.get("CCP_STAGE"):  # child mode
        _stage_main(os.environ["CCP_STAGE"])
        return

    timeout = int(os.environ.get("CCP_TIMEOUT", "600"))
    stages = (os.environ.get("CCP_STAGES", "").split(",")
              if os.environ.get("CCP_STAGES") else STAGES)
    out_path = os.environ.get("CCP_OUT",
                              os.path.join(REPO, "COMPILE_PROBE.json"))
    results = []
    for stage in stages:
        print(f"--- {stage} (timeout {timeout}s)", file=sys.stderr,
              flush=True)
        env = {**os.environ, "CCP_STAGE": stage}
        r = run_guarded([sys.executable, os.path.abspath(__file__)],
                        timeout, env=env)
        rec = {"stage": stage, "rc": r.rc, "timed_out": r.timed_out,
               "wall_s": round(r.wall_s, 1)}
        for line in (r.stdout or "").splitlines():
            try:
                rec.update(json.loads(line))
                break
            except json.JSONDecodeError:
                continue
        if not rec.get("ok"):
            rec["stderr_tail"] = (r.stderr or "")[-800:]
        results.append(rec)
        print(json.dumps(rec), file=sys.stderr, flush=True)
        with open(out_path, "w") as fh:
            json.dump({"stages": results, "lib": LIB}, fh, indent=1)
        if stage == "s0_probe" and (r.timed_out or r.rc != 0):
            print("chip unreachable; aborting ladder", file=sys.stderr)
            break
        if r.timed_out and os.environ.get("CCP_ABORT_ON_TIMEOUT") == "1":
            # round-4 lesson: the SIGTERM'd mid-compile client likely
            # wedged the tunnel, so every later stage would measure the
            # wedge, not the program — stop and leave the chip alone
            print("stage timed out; aborting ladder "
                  "(CCP_ABORT_ON_TIMEOUT)", file=sys.stderr)
            break
    print(json.dumps({"stages": results}))


if __name__ == "__main__":
    main()
