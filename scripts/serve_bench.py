#!/usr/bin/env python
"""Serving load generator: seeded Poisson trace -> cond/s + latency.

The PERF.md round-10 evidence format for the serving plane: stand up a
daemon (in-process by default, over REAL localhost HTTP; ``--url``
targets an external one), warm its AOT program set, fire a SEEDED
open-loop Poisson request trace through ``serving.client``, and report
sustained cond/s, p50/p95/p99 latency, scheduler rejections, and the
``compiles == 0`` check over the serving window.

  # 40 requests at ~20 req/s against the vendored h2o2 spec
  python scripts/serve_bench.py --spec tests/fixtures/serve_h2o2.json \\
      --requests 40 --rate 20 --seed 0 --out /tmp/serve_bench.json

  # CI smoke flags: scrape /metrics mid-trace, require every request
  # answered with per-lane success provenance
  python scripts/serve_bench.py --spec ... --scrape-out /tmp/serve.prom \\
      --require-success

The trace randomizes T within ``--T-lo/--T-hi`` and lane counts within
``--lanes`` (e.g. ``1,4``) from the seed's own rng, so two runs of one
seed issue identical schedules AND identical conditions — a throughput
delta is the server's, not the load's.

Requests carry ``trace: true`` by default (``--no-trace`` reverts to
the round-10 request shape), so the summary reports the SERVER-side
stage decomposition (obs/trace.py waterfall stages, p50/p95 per stage)
next to the client percentiles, and every answered request's client
``latency_s`` is checked against the server ``submitted -> resolved``
wall: server <= client always (the server cannot out-wait its own
caller), and the gap — HTTP + JSON + thread-wakeup overhead — must
stay under ``--attribution-tol-ms``, which catches clock and
stage-attribution bugs (the seeded ``slow_request`` injection makes
the stalled stage deterministic).  ``--obs-out`` banks the in-process
session's obs report JSONL (histograms + request_trace events), the
``scripts/obs_gate.py`` / ``obs_trace.py`` input.

Fleet mode (``--router N``) additionally attaches a deterministic
``trace_ctx`` envelope per request (trace id ``t-<request id>``),
stitches the members' and the router's trace streams in-process after
the run (``obs.stitch``), and extends the attribution check ACROSS the
router hop: client latency must cover each request's stitched
end-to-end wall.  ``--obs-out`` then banks the MERGED fleet report
(router ``route_seconds`` beside every member's
``serve_stage_seconds``).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", help="session spec JSON (required unless "
                                   "--url targets a running daemon)")
    ap.add_argument("--url", help="bench an already-running daemon "
                                  "instead of standing one up")
    ap.add_argument("--requests", type=int, default=40)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="mean request arrivals per second")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--lanes", default="1,4",
                    help="lane-count choices per request, comma list")
    ap.add_argument("--T-lo", type=float, default=1100.0)
    ap.add_argument("--T-hi", type=float, default=1500.0)
    ap.add_argument("--comp", default="H2=0.3,O2=0.15,N2=0.55",
                    help="inlet mole fractions, SP=x comma-separated")
    ap.add_argument("--mechs", action="append", default=[],
                    metavar="ID=MECH:THERM",
                    help="multi-mechanism preset: upload these extra "
                         "mechanisms over POST /mechanism before the "
                         "trace and route requests across the whole set "
                         "from the seed's rng; the summary gains "
                         "per-mechanism cond/s + the compile/wall "
                         "split (PERF.md round-11).  Repeatable; "
                         "in-process daemons get the session store "
                         "automatically")
    ap.add_argument("--t1", type=float, default=5e-5,
                    help="integration horizon per request [s]")
    ap.add_argument("--t1-choices",
                    help="comma list of t1 horizons drawn per request "
                         "from the seed's rng (fleet benches: t1 is part "
                         "of the routing key, so a spread of horizons "
                         "spreads load across the hash ring; a single "
                         "t1 legitimately pins every request to ONE "
                         "member — that is affinity working)")
    ap.add_argument("--router", type=int, default=0, metavar="N",
                    help="fleet mode: stand up N in-process member "
                         "daemons + the consistent-hash router "
                         "(fleet.FleetRouter) and bench THROUGH the "
                         "router; the summary gains per-host cond/s "
                         "and the direct-vs-failover latency split")
    ap.add_argument("--fleet-dir",
                    help="fleet membership dir for --router (default: "
                         "a fresh temp dir)")
    ap.add_argument("--epochs", type=int, metavar="N",
                    help="override the spec's serve.resident_epochs "
                         "(capacity plane, docs/performance.md "
                         "\"Capacity levers\"): N resident streaming "
                         "epochs pull from one shared admission queue; "
                         "the A/B lever for the multi-epoch PERF "
                         "rounds (needs --spec)")
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    ap.add_argument("--out", help="write the summary JSON here too")
    ap.add_argument("--scrape-out",
                    help="save a MID-TRACE /metrics scrape here (the CI "
                         "serve-smoke artifact)")
    ap.add_argument("--require-success", action="store_true",
                    help="exit 1 unless every request is answered ok "
                         "with all-success per-lane provenance (and, "
                         "with traces on, client~server latency "
                         "attribution within tolerance)")
    ap.add_argument("--no-trace", action="store_true",
                    help="drop the trace:true request key (the "
                         "round-10 request shape; disables the "
                         "server-stage summary + attribution check)")
    ap.add_argument("--attribution-tol-ms", type=float, default=2000.0,
                    help="max client latency minus server "
                         "submitted->resolved wall per request "
                         "(transport + client-thread-wakeup overhead; "
                         "p50 is ~20 ms but open-loop thread "
                         "contention spikes the tail, so the default "
                         "stays CI-loose — an attribution BUG shows "
                         "as server > client or a gap of order the "
                         "total latency, far outside any band here)")
    ap.add_argument("--obs-out",
                    help="write the in-process session's obs report "
                         "JSONL here after the trace (histograms + "
                         "request_trace events; the obs_gate.py / "
                         "obs_trace.py input — needs --spec).  With "
                         "--router, writes the MERGED fleet report "
                         "(router route_seconds + every member's "
                         "serve_stage_seconds, obs.stitch."
                         "merge_reports)")
    args = ap.parse_args(argv)
    if not args.url and not args.spec:
        ap.error("--spec (in-process daemon) or --url (external) needed")
    if args.epochs is not None and not args.spec:
        ap.error("--epochs overrides the spec's serve.resident_epochs; "
                 "it needs --spec (an external daemon fixes its own)")
    spec_arg = args.spec
    if args.epochs is not None:
        with open(args.spec) as fh:
            spec_arg = json.load(fh)
        # a dict spec loses the file's directory, so pre-resolve the
        # relative mechanism paths the way load_spec(path) would
        base = os.path.dirname(os.path.abspath(args.spec))
        for k in ("mech", "therm"):
            p = (spec_arg.get("mechanism") or {}).get(k)
            if isinstance(p, str) and not os.path.isabs(p):
                spec_arg["mechanism"][k] = os.path.join(base, p)
        spec_arg.setdefault("serve", {})["resident_epochs"] = args.epochs
    if args.obs_out and args.url:
        ap.error("--obs-out reads the in-process session's recorder; "
                 "use --spec (an external daemon writes its own via "
                 "scripts/serve.py --obs-out)")
    if args.router:
        if args.url:
            ap.error("--router stands up its own fleet; to bench an "
                     "external fleet, point --url at its router")
        if args.mechs:
            ap.error("--router does not combine with --mechs "
                     "(one session store vs N hosts)")

    from batchreactor_tpu.serving.client import (SolveClient,
                                                 poisson_trace,
                                                 run_trace,
                                                 stitched_attribution,
                                                 summarize,
                                                 trace_summary,
                                                 with_trace_ctx)

    comp = {}
    for part in args.comp.split(","):
        name, _, val = part.partition("=")
        comp[name.strip()] = float(val)
    lane_choices = [int(v) for v in args.lanes.split(",")]
    mech_specs = []
    for spec_str in args.mechs:
        mid, _, rest = spec_str.partition("=")
        mech, _, therm = rest.partition(":")
        if not (mid and mech and therm):
            ap.error(f"--mechs wants ID=MECH:THERM, got {spec_str!r}")
        mech_specs.append((mid, mech, therm))
    #: the routing choices the seeded rng draws from — None is the
    #: daemon's default mechanism; uploads join before the trace fires
    mech_choices = [None] + [m[0] for m in mech_specs]
    t1_choices = ([float(v) for v in args.t1_choices.split(",")]
                  if args.t1_choices else [args.t1])

    def make_request(i, rng):
        k = rng.choice(lane_choices)
        t1 = args.t1
        if len(t1_choices) > 1:
            # draw only with a real spread: an unconditional draw would
            # consume rng state and change every seeded baseline trace
            t1 = rng.choice(t1_choices)
        req = {"id": f"bench-{args.seed}-{i}",
               "T": [round(rng.uniform(args.T_lo, args.T_hi), 3)
                     for _ in range(k)],
               "X": comp, "t1": t1}
        if not args.no_trace:
            # no rng draw: the seeded schedule/conditions stay
            # identical to the round-10 baselines with traces on or off
            req["trace"] = True
            # the distributed-trace envelope is deterministic too
            # (trace id t-<request id> — with_trace_ctx), so the bench
            # can join each client record against its stitched fleet
            # trace without responses carrying ids
            req = with_trace_ctx(req)
        if len(mech_choices) > 1:
            # draw only in multi-mechanism mode: an unconditional draw
            # would consume rng state and silently change every seeded
            # single-mechanism trace vs the round-10 baselines
            mech = rng.choice(mech_choices)
            if mech is not None:
                req["mech"] = mech
        return req

    trace = poisson_trace(args.requests, args.rate, args.seed,
                          make_request)

    session = server = store = None
    fleet_hosts, fleet_router = [], None
    if args.url:
        url = args.url
    elif args.router:
        # fleet mode: N member daemons in-process (real localhost HTTP
        # each), registered into one fleet dir, benched THROUGH the
        # consistent-hash router — requests spread across hosts only as
        # far as their routing keys spread (--t1-choices)
        import tempfile

        from batchreactor_tpu import aot

        if args.cache_dir:
            aot.configure_cache(args.cache_dir)
        from batchreactor_tpu.fleet import FleetRouter, MemberRegistration
        from batchreactor_tpu.serving.scheduler import Scheduler
        from batchreactor_tpu.serving.server import ServingServer
        from batchreactor_tpu.serving.session import SolverSession

        fleet_dir = args.fleet_dir or tempfile.mkdtemp(
            prefix="br-fleet-bench-")
        for i in range(args.router):
            name = f"m{i + 1}"
            s = SolverSession.from_spec(spec_arg)
            if not args.no_warmup:
                s.warmup(cache_dir=args.cache_dir,
                         log=lambda m: print(m, file=sys.stderr),
                         manifest_tag=name)
            s.__enter__()
            srv = ServingServer(s, Scheduler(s)).start()
            srv.membership = MemberRegistration(
                fleet_dir, name, srv.url, registry=s.registry,
                pid=f"{os.getpid()}-{name}").register()
            fleet_hosts.append((name, s, srv))
            print(f"[serve-bench] fleet member {name} @ {srv.url}",
                  file=sys.stderr)
        fleet_router = FleetRouter(fleet_dir).start()
        url = fleet_router.url
    else:
        from batchreactor_tpu import aot

        if args.cache_dir:
            aot.configure_cache(args.cache_dir)
        from batchreactor_tpu.serving.scheduler import Scheduler
        from batchreactor_tpu.serving.server import ServingServer
        from batchreactor_tpu.serving.session import (SessionStore,
                                                      SolverSession)

        session = SolverSession.from_spec(spec_arg)
        if not args.no_warmup:
            session.warmup(cache_dir=args.cache_dir,
                           log=lambda m: print(m, file=sys.stderr))
        session.__enter__()
        scheduler = Scheduler(session)
        if mech_specs:
            store = SessionStore(session, scheduler,
                                 cache_dir=args.cache_dir)
        server = ServingServer(session, scheduler, store=store).start()
        url = server.url

    client = SolveClient(url)
    upload_s = 0.0
    if mech_specs:
        # the upload path IS the measured surface: route the extra
        # mechanisms through POST /mechanism like any client would
        # (works against --url daemons too), timing the warm-in wall
        t_up = time.perf_counter()
        for mid, mech, therm in mech_specs:
            with open(mech) as f:
                mech_text = f.read()
            with open(therm) as f:
                therm_text = f.read()
            resp = client.upload_mechanism(mid, mech_text, therm_text,
                                           warm=not args.no_warmup)
            print(f"[serve-bench] mechanism {mid!r} resident "
                  f"(shape {resp.get('mech_shape')}, armed compiles "
                  f"{sum((resp.get('program_compiles') or {}).values())})",
                  file=sys.stderr)
        upload_s = time.perf_counter() - t_up
    scrapes = []
    answered = [0]

    def on_result(_rec):
        answered[0] += 1
        # one mid-trace scrape once the stream is demonstrably hot
        if args.scrape_out and len(scrapes) < 1 and answered[0] >= max(
                2, args.requests // 4):
            try:
                scrapes.append(client.metrics())
            except OSError:
                pass

    print(f"[serve-bench] {args.requests} requests @ ~{args.rate}/s "
          f"(seed {args.seed}) -> {url}", file=sys.stderr)
    t0 = time.perf_counter()
    records = run_trace(client, trace, on_result=on_result)
    wall = time.perf_counter() - t0
    if args.scrape_out and not scrapes:
        try:
            scrapes.append(client.metrics())
        except OSError:
            pass

    summary = summarize(records, wall)
    summary["seed"] = args.seed
    summary["rate_hz"] = args.rate
    summary["t1"] = args.t1
    if mech_specs:
        # per-mechanism split: lanes answered / shared trace wall (the
        # mechanisms ride ONE daemon, so per-mechanism cond/s sum to
        # the total) + the upload/warm-in wall
        per = {}
        for (_at, req), rec in zip(trace, records):
            key = req.get("mech") or "default"
            d = per.setdefault(key, {"requests": 0, "answered": 0,
                                     "lanes": 0})
            d["requests"] += 1
            if rec and rec["ok"]:
                d["answered"] += 1
                d["lanes"] += len((rec["response"] or {}).get("t", []))
        for d in per.values():
            d["cond_per_s"] = (round(d["lanes"] / wall, 3)
                               if wall > 0 else None)
        summary["per_mechanism"] = per
        summary["mech_upload_s"] = round(upload_s, 3)
    all_success = all(
        r and r["ok"]
        and all(p == "success"
                for p in (r["response"] or {}).get("provenance", ["x"]))
        for r in records)
    summary["all_success"] = bool(all_success)

    # the server-side half of the evidence: stage decomposition next to
    # the client percentiles + the client~server attribution check
    # (serving.client.trace_summary — a violation is a clock or
    # stage-attribution bug)
    attribution_ok = True
    tsum = trace_summary(records,
                         attribution_tol_ms=args.attribution_tol_ms)
    if tsum is not None:
        attribution_ok = tsum["attribution"]["ok"]
        summary.update(tsum)
        if not attribution_ok:
            print(f"[serve-bench] ATTRIBUTION violations (first 8): "
                  f"{tsum['attribution']['violations']}",
                  file=sys.stderr)

    if fleet_router is not None:
        # the fleet evidence: where each answer came from (response
        # provenance from the router's "router" block), per-host
        # cond/s, and the direct-vs-failover latency split
        per_host = {}
        direct, failover = [], []
        for rec in records:
            if not rec:
                continue
            rinfo = (rec["response"] or {}).get("router") or {}
            host = rinfo.get("host", "?")
            d = per_host.setdefault(host, {"requests": 0, "answered": 0,
                                           "lanes": 0, "failovers": 0})
            d["requests"] += 1
            if rec["ok"]:
                d["answered"] += 1
                d["lanes"] += len((rec["response"] or {}).get("t", []))
            if rinfo.get("failover"):
                d["failovers"] += 1
                failover.append(rec["latency_s"])
            else:
                direct.append(rec["latency_s"])
        for d in per_host.values():
            d["cond_per_s"] = (round(d["lanes"] / wall, 3)
                               if wall > 0 else None)

        def _lat(vals):
            if not vals:
                return None
            vals = sorted(vals)

            def _pct(p):
                k = min(len(vals) - 1, max(0, round(p * (len(vals) - 1))))
                return round(vals[int(k)] * 1e3, 1)

            return {"n": len(vals), "p50_ms": _pct(0.5),
                    "p95_ms": _pct(0.95), "max_ms": _pct(1.0)}

        summary["fleet"] = {
            "hosts": args.router,
            "per_host": per_host,
            "latency_direct": _lat(direct),
            "latency_failover": _lat(failover)}
        # per-host compile evidence: the warm-serving contract holds on
        # every member, not just in aggregate
        summary["per_host_compiles"] = {}
        for name, s, srv in fleet_hosts:
            srv.close()   # drain handshake: mark_draining -> deregister
            summary["per_host_compiles"][name] = s.program_compiles()
        summary["program_compiles"] = sum(
            sum(d.values()) for d in summary["per_host_compiles"].values())
        fleet_router.close()

        # the stitched cross-host story (docs/observability.md "Fleet
        # tracing"): every member's trace stream + the router's hop
        # ledger joined in-process — the PR-15 attribution check
        # EXTENDED across the router hop (client latency must cover
        # the stitched end-to-end wall)
        from batchreactor_tpu.obs import build_report
        from batchreactor_tpu.obs.stitch import merge_reports
        from batchreactor_tpu.obs.stitch import stitch as stitch_fleet

        fleet_reports = [(name, s.obs_report())
                         for name, s, _srv in fleet_hosts]
        fleet_reports.append(("router", build_report(
            recorder=fleet_router.recorder,
            meta={"entry": "fleet-router", "bench_seed": args.seed,
                  "bench_rate_hz": args.rate})))
        stitched = stitch_fleet(fleet_reports)
        if not args.no_trace:
            sattr = stitched_attribution(
                records, stitched,
                attribution_tol_ms=args.attribution_tol_ms)
            if sattr is not None:
                summary["fleet"]["stitched_attribution"] = sattr
                attribution_ok = attribution_ok and sattr["ok"]
                if not sattr["ok"]:
                    print(f"[serve-bench] STITCHED attribution "
                          f"violations (first 8): "
                          f"{sattr['violations']}", file=sys.stderr)
        if args.obs_out:
            from batchreactor_tpu.obs import write_jsonl

            write_jsonl(args.obs_out, merge_reports(fleet_reports))
            print(f"[serve-bench] merged fleet obs report -> "
                  f"{args.obs_out}", file=sys.stderr)

        for _name, s, _srv in fleet_hosts:
            s.__exit__(None, None, None)

    if server is not None:
        if store is not None:
            # the compile/wall split per resident mechanism — the
            # round-11 evidence that shared-rung mechanisms serve a
            # whole trace at zero armed compiles
            summary["per_mechanism_compiles"] = {
                "+".join(m["ids"]) or m["fingerprint"][:12]:
                    m["program_compiles"]
                for m in store.mechanisms()}
        server.close()
        if args.obs_out:
            from batchreactor_tpu.obs import write_jsonl

            write_jsonl(args.obs_out, session.obs_report(
                meta={"bench_seed": args.seed,
                      "bench_rate_hz": args.rate}))
            print(f"[serve-bench] obs report -> {args.obs_out}",
                  file=sys.stderr)
        w = session.compile_summary()
        # the capacity-plane levers this run served under + their
        # autoscaler evidence (ISSUE 20): the A/B axes of the
        # multi-epoch PERF rounds ride every summary
        summary["resident_epochs"] = int(
            getattr(session, "resident_epochs", 1))
        summary["mesh_resident"] = getattr(session, "mesh_resident",
                                           None)
        summary["bucket_upshifts"] = int(
            session.recorder.snapshot()[2].get("bucket_upshifts", 0))
        # program_compiles is the warm-serving contract (0 after
        # warmup); "compiles" totals additionally count sub-ms host
        # eager-op programs on the unarmed serve-host label
        summary["program_compiles"] = session.program_compiles()
        summary["compiles"] = w["compiles"]
        summary["compile_s"] = round(w.get("compile_s", 0.0), 3)
        summary["retraces"] = w["retraces"]
        summary["cache_hits"] = w["cache_hits"]
        session.__exit__(None, None, None)
    if scrapes and args.scrape_out:
        with open(args.scrape_out, "w") as fh:
            fh.write(scrapes[-1])
        print(f"[serve-bench] mid-trace scrape -> {args.scrape_out}",
              file=sys.stderr)
    print(json.dumps(summary, indent=1))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(summary, fh, indent=1)
    if args.require_success and not (all_success and attribution_ok):
        if not all_success:
            bad = [r["id"] for r in records
                   if not (r and r["ok"])][:8]
            print(f"[serve-bench] FAILED requests (first 8): {bad}",
                  file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
