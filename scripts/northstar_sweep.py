"""North-star workload: 4096-condition GRI-Mech 3.0 ignition map on TPU.

The BASELINE.md target: >= 50x wall-clock vs single-CPU CVODE-class BDF on a
4096-condition GRI ignition sweep, < 1% ignition-delay error.  The reference
can only do this as 4096 serial CVODE calls (one condition per call,
/root/reference/src/BatchReactor.jl:210); here it is ONE checkpointed,
mesh-shardable, segmented ensemble program.

Grid: 64 T0 x 64 phi (equivalence ratio), CH4/O2/N2 with the oxidizer
stream carrying N2 at the reference batch_ch4 ratio (phi=1 reproduces its
0.25/0.5/0.25 mixture, /root/reference/test/batch_ch4/batch.xml), 1 bar,
t1 = 8e-4 s, rtol 1e-6 / atol 1e-10 (the reference's CVODE tolerances).
Ignition delay tau = first accepted time CH4 drops below half its initial
value, extracted in-loop by the O(B) observer fold (no trajectory buffer).

Outputs NORTHSTAR.json: conditions/sec, tau parity vs the native C++ BDF
(independent implementation) on spot-check lanes, per-status lane counts,
and the phase-timer breakdown (parse / build / solve).

Usage:
  python scripts/northstar_sweep.py                 # full 4096 on the device
  NORTHSTAR_NT=4 NORTHSTAR_NPHI=2 ...               # small grids (tests/CI)
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
# match the bench protocol (bench.py rung_main): f32 rate exponentials on
# by default, BR_EXP32=0 reverts; must be set before the package import
os.environ.setdefault("BR_EXP32", "1")

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")


def _lane_cost_model(T, phi, log=print):
    """Predicted per-lane cost from the stratified single-core sample in
    NORTHSTAR_BASELINE.json (scripts/northstar_baseline.py): bilinear
    interpolation of the native-BDF s/lane over the (T, phi) plane.  Used
    to cost-sort lanes before chunking (checkpointed_sweep lane_cost=):
    a chunk's wall is its slowest lane, and the map's corner lanes cost
    ~3x its cheap lanes, so cost-homogeneous chunks cut the straggler
    tax.  Ordering is all that matters; absolute calibration does not.
    Returns None (no sort) if the baseline artifact is unavailable."""
    import numpy as np

    path = os.path.join(REPO, "NORTHSTAR_BASELINE.json")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        rec = json.load(fh)
    per_lane = rec.get("per_lane")
    if not per_lane:
        return None
    pts = np.asarray([[r["T"], r["phi"]] for r in per_lane])
    # all-or-nothing solver choice: native_s and scipy_s differ ~3.6x in
    # absolute scale, so a per-row fallback would order lanes by which
    # solver timed them, not by cost
    key = ("native_s" if all("native_s" in r for r in per_lane)
           else "scipy_s" if all("scipy_s" in r for r in per_lane)
           else None)
    if key is None:
        return None
    w = np.asarray([r[key] for r in per_lane])
    if np.isnan(w).any():
        return None
    Tg = np.unique(pts[:, 0])
    Pg = np.unique(pts[:, 1])
    if Tg.size * Pg.size != w.size:
        return None
    W = w.reshape(Tg.size, Pg.size)  # lanes were written T-major

    def interp1(grid, x):
        i = np.clip(np.searchsorted(grid, x) - 1, 0, grid.size - 2)
        f = np.clip((x - grid[i]) / (grid[i + 1] - grid[i]), 0.0, 1.0)
        return i, f

    iT, fT = interp1(Tg, np.asarray(T))
    iP, fP = interp1(Pg, np.asarray(phi))
    cost = ((1 - fT) * (1 - fP) * W[iT, iP]
            + (1 - fT) * fP * W[iT, iP + 1]
            + fT * (1 - fP) * W[iT + 1, iP]
            + fT * fP * W[iT + 1, iP + 1])
    log(f"[northstar] lane-cost model from {os.path.basename(path)}: "
        f"predicted s/lane {cost.min():.3f}..{cost.max():.3f} "
        f"(max/mean {cost.max() / cost.mean():.2f})")
    return cost


def run_sweep(n_T=64, n_phi=64, T_lo=1500.0, T_hi=2000.0, phi_lo=0.6,
              phi_hi=1.6, t1=8e-4, p=1e5, ckpt_dir=None, chunk_size=512,
              segment_steps=256, mesh=None, rtol=1e-6, atol=1e-10,
              n_spot=8, method="bdf", jac_window=8, sort_lanes=True,
              pipeline=None, poll_every=None, admission=None, refill=None,
              record_occupancy=False, energy=None, log=print):
    """Run the T x phi GRI ignition map; return the result record dict.

    ``energy`` (NORTHSTAR_ENERGY=0/1 — docs/energy.md) switches the map
    to the adiabatic constant-volume family: the state grows the
    trailing T row, tau comes from the physical max-dT/dt detector
    instead of the CH4 half-consumption proxy, and the native-BDF spot
    check is skipped (the C++ runtime is isothermal-only).  The A/B
    pair at one grid is the next healthy-chip lever: expect the
    stiffness spike at ignition to shift the order histogram down and
    the err-reject count up (PERF.md round-12 has the CPU signature)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.parallel import ignition_observer
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
    from batchreactor_tpu.parallel.grid import (condition_grid,
                                                premixed_mole_fracs,
                                                sweep_solution_vectors)
    from batchreactor_tpu.parallel.sweep import (ensemble_solve_segmented,
                                                 resolve_pipeline_defaults)
    from batchreactor_tpu.parallel import sweep_report
    from batchreactor_tpu.solver.sdirk import SUCCESS
    from batchreactor_tpu.utils.profiling import Phases

    ph = Phases()
    with ph("parse"):
        gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
        th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sp = list(gm.species)

    with ph("build"):
        grid = condition_grid(T=jnp.linspace(T_lo, T_hi, n_T),
                              phi=jnp.linspace(phi_lo, phi_hi, n_phi))
        B = grid["T"].shape[0]
        # oxidizer stream carries N2 at 0.5 mol per mol O2: phi=1 gives the
        # reference batch_ch4 mixture CH4/O2/N2 = 0.25/0.5/0.25
        X = premixed_mole_fracs(sp, "CH4", grid["phi"], stoich_o2=2.0,
                                diluent="N2", o2_to_diluent=0.5)
        y0s = sweep_solution_vectors(X, th.molwt, grid["T"], p)
        cfgs = {"T": grid["T"]}
        if energy is not None:
            from batchreactor_tpu.energy import (
                energy_atol_scale, energy_ignition_observer,
                make_energy_jac, make_energy_rhs)
            from batchreactor_tpu.solver.sdirk import ATOL_SCALE_KEY

            rhs = make_energy_rhs(gm, th, energy)
            jac = make_energy_jac(gm, th, energy)
            obs, obs0 = energy_ignition_observer(len(sp))
            y0s = jnp.concatenate([y0s, grid["T"][:, None]], axis=1)
            cfgs[ATOL_SCALE_KEY] = energy_atol_scale(
                B, int(y0s.shape[1]), atol)
        else:
            rhs = make_gas_rhs(gm, th)
            jac = make_gas_jac(gm, th)
            obs, obs0 = ignition_observer(sp.index("CH4"), mode="half")

    solve_kw = dict(rtol=rtol, atol=atol, jac=jac, observer=obs,
                    observer_init=obs0, mesh=mesh, method=method,
                    segment_steps=segment_steps, jac_window=jac_window,
                    pipeline=pipeline, poll_every=poll_every)
    # continuous batching (NORTHSTAR_ADMISSION): an obs Recorder rides
    # along so the occupancy split lands in the record either way the
    # knob is set — that pair is the A/B evidence the map-vs-rung gap
    # analysis needs (PERF.md)
    from batchreactor_tpu.obs import Recorder
    from batchreactor_tpu.obs.live import arm_flight

    obs_rec = (Recorder() if (admission is not None or record_occupancy)
               else None)
    # flight recorder armed for every northstar run (docs/observability
    # .md "Flight recorder"): chip_session drives this script under
    # resilience.run_guarded, whose teardown is SIGTERM-with-grace — the
    # SIGTERM hook dumps flight_<ts>.jsonl next to the output, so the
    # next on-chip wedge postmortem ships evidence instead of a bare
    # SIGTERM note.  The watchdog/retry fault paths dump through the
    # same ring.
    arm_flight(recorder=obs_rec,
               dir=os.path.dirname(os.environ.get(
                   "NORTHSTAR_OUT", os.path.join(REPO, "NORTHSTAR.json")))
               or ".",
               install_signal=True)
    lane_cost = None
    if sort_lanes and ckpt_dir:
        # cost-sorted chunking only changes anything when the sweep is
        # chunked; the single-program path has no chunk boundaries
        lane_cost = _lane_cost_model(grid["T"], grid["phi"], log=log)
    t_start = time.perf_counter()
    with ph("solve"):
        if ckpt_dir:
            res = checkpointed_sweep(rhs, y0s, 0.0, t1, cfgs, ckpt_dir,
                                     chunk_size=chunk_size,
                                     lane_cost=lane_cost, chunk_log=log,
                                     admission=admission, refill=refill,
                                     recorder=obs_rec, energy=energy,
                                     **solve_kw)
        else:
            kw = {k: v for k, v in solve_kw.items() if k != "segment_steps"}
            res = ensemble_solve_segmented(rhs, y0s, 0.0, t1, cfgs,
                                           segment_steps=segment_steps,
                                           admission=admission,
                                           refill=refill,
                                           recorder=obs_rec, **kw)
        jax.block_until_ready(res.y)
    wall = time.perf_counter() - t_start
    occ = None
    adm_ctrs = {}
    if obs_rec is not None:
        from batchreactor_tpu.obs import counters as _C

        adm_ctrs = obs_rec.snapshot()[2]
        occ = _C.occupancy(adm_ctrs)

    if energy is not None:
        from batchreactor_tpu.energy import extract_delay

        tau = np.asarray(extract_delay(res.observed))
    else:
        tau = np.asarray(res.observed["tau"])
    status = np.asarray(res.status)
    if segment_steps and int(segment_steps) > 0:
        gear_run, stride_run = resolve_pipeline_defaults(pipeline,
                                                         poll_every)
    else:
        # monolithic launch (NORTHSTAR_SEG=0): no segmented gear ran at
        # all — record null, not a resolved default that never executed
        gear_run = stride_run = None
    report = sweep_report(res, cfgs)
    log(f"[northstar] B={B} wall={wall:.1f}s -> {B / wall:.2f} cond/s "
        f"({int((status == SUCCESS).sum())}/{B} ok, "
        f"{int(np.isnan(tau).sum())} no-ignition)")
    log("[northstar] phases:\n" + ph.pretty())

    # --- tau parity spot-check against the independent native C++ BDF ----
    parity = None
    spot = []
    if energy is not None:
        # the native C++ BDF oracle is isothermal-only: no parity spot
        # check exists for the adiabatic family yet (recorded as null,
        # not silently green)
        n_spot = 0
    if n_spot:
        from batchreactor_tpu import native

        ign = np.nonzero(~np.isnan(tau) & (status == SUCCESS))[0]
        idx = ign[np.linspace(0, ign.size - 1, min(n_spot, ign.size))
                  .astype(int)] if ign.size else []
        x_np = np.asarray(X)
        ch4 = sp.index("CH4")
        with ph("spot_check"):
            for b in idx:
                y0b = np.asarray(y0s[b])
                rn = native.solve_gas_bdf(gm, th, float(grid["T"][b]), y0b,
                                          0.0, t1, rtol=rtol, atol=atol,
                                          n_save=100_000)
                ts = np.concatenate([[0.0], np.asarray(rn.ts)])
                ys = np.concatenate([y0b[None, :], np.asarray(rn.ys)])
                thr = 0.5 * y0b[ch4]
                below = ys[:, ch4] < thr
                if below.any():
                    i = int(np.argmax(below))
                    if i == 0:
                        tau_n = float(ts[0])
                    else:  # interpolate the crossing like the observer does
                        m_a, m_b = ys[i - 1, ch4], ys[i, ch4]
                        w = (m_a - thr) / (m_a - m_b) if m_a != m_b else 1.0
                        tau_n = float(ts[i - 1] + w * (ts[i] - ts[i - 1]))
                else:
                    tau_n = np.nan
                rel = abs(tau_n - tau[b]) / tau_n if tau_n else np.nan
                spot.append({"lane": int(b), "T": float(grid["T"][b]),
                             "phi": float(grid["phi"][b]),
                             "tau_tpu": float(tau[b]), "tau_native": tau_n,
                             "rel_err": float(rel)})
                log(f"[spot] lane {b}: T={float(grid['T'][b]):.0f} "
                    f"phi={float(grid['phi'][b]):.2f} "
                    f"tau={float(tau[b]):.4e} native={tau_n:.4e} "
                    f"rel={rel:.2%}")
        # a NaN rel_err (native BDF disagreed about ignition itself) must fail
    # the parity claim loudly, not vanish in max()'s NaN ordering; None +
    # a failure count keeps the JSON RFC-8259 (inf/nan are not valid JSON)
    failed_spots = sum(s["rel_err"] != s["rel_err"] for s in spot)
    finite = [s["rel_err"] for s in spot if s["rel_err"] == s["rel_err"]]
    parity = None if failed_spots else (max(finite) if finite else None)

    return {
        "workload": f"GRI30 {n_T}x{n_phi} TxPhi ignition map, 1 bar, "
                    f"t1={t1}, rtol={rtol} atol={atol}"
                    + (f", energy={energy}" if energy else ""),
        # NORTHSTAR_ENERGY: null = isothermal reference physics, else
        # the adiabatic mode the map ran (docs/energy.md)
        "energy": energy,
        "method": method,
        "exp32": os.environ.get("BR_EXP32") == "1",
        "jac_window": jac_window,
        # the segmented execution gear actually run (None resolves through
        # the ONE library rule, so the record can't drift from reality)
        "pipeline": gear_run,
        "poll_every": stride_run,
        # continuous batching (NORTHSTAR_ADMISSION=0/1/N): resident knob
        # + the occupancy split of this run — the A/B pair for the
        # map-vs-rung gap (null occupancy = no recorder armed)
        "admission": (admission if not isinstance(admission, bool)
                      else "chunk"),
        "occupancy": None if occ is None else round(occ, 6),
        "admitted_lanes": int(adm_ctrs.get("admitted_lanes", 0)),
        "bucket_downshifts": int(adm_ctrs.get("bucket_downshifts", 0)),
        "lane_cost_sorted": lane_cost is not None,
        "B": int(B),
        "wall_s": round(wall, 2),
        "cond_per_s": round(B / wall, 3),
        "device": jax.default_backend(),
        "counts": report["counts"],
        "n_no_ignition": int(np.isnan(tau).sum()),
        "tau_range_s": [float(np.nanmin(tau)), float(np.nanmax(tau))],
        "tau_parity_max_rel_err": parity,
        "tau_parity_failed_spots": (sum(s["rel_err"] != s["rel_err"]
                                        for s in spot) if spot else 0),
        "spot_checks": spot,
        "phases_s": {k: round(v, 2) for k, v in ph.summary().items()},
    }


def main():
    import jax

    if os.environ.get("NORTHSTAR_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    n_T = int(os.environ.get("NORTHSTAR_NT", "64"))
    n_phi = int(os.environ.get("NORTHSTAR_NPHI", "64"))
    ckpt = os.environ.get("NORTHSTAR_CKPT", "")
    rec = run_sweep(n_T=n_T, n_phi=n_phi, ckpt_dir=ckpt or None,
                    method=os.environ.get("NORTHSTAR_METHOD", "bdf"),
                    # jw=8 validated for BDF only (PERF.md); sdirk keeps 1
                    jac_window=int(os.environ.get(
                        "NORTHSTAR_JW",
                        "8" if os.environ.get("NORTHSTAR_METHOD",
                                              "bdf") == "bdf" else "1")),
                    segment_steps=int(os.environ.get("NORTHSTAR_SEG", "256")),
                    chunk_size=int(os.environ.get("NORTHSTAR_CHUNK", "512")),
                    sort_lanes=os.environ.get("NORTHSTAR_SORT", "1") == "1",
                    # NORTHSTAR_PIPELINE=0 pins the blocking gear for this
                    # run regardless of the BENCH_PIPELINE library default
                    pipeline=(None if "NORTHSTAR_PIPELINE" not in os.environ
                              else os.environ["NORTHSTAR_PIPELINE"] != "0"),
                    poll_every=(None if "NORTHSTAR_POLL" not in os.environ
                                else int(os.environ["NORTHSTAR_POLL"])),
                    # NORTHSTAR_ADMISSION: 0/unset = off, 1 = on with the
                    # chunk-sized resident program (checkpointed backlog
                    # mode), N > 1 = explicit resident lane count.  The
                    # env present at all (either side) arms the occupancy
                    # recorder, so A/B rounds diff one ratio.
                    admission=(None if os.environ.get(
                        "NORTHSTAR_ADMISSION", "0") == "0"
                        else True if os.environ["NORTHSTAR_ADMISSION"] == "1"
                        else int(os.environ["NORTHSTAR_ADMISSION"])),
                    record_occupancy="NORTHSTAR_ADMISSION" in os.environ,
                    # NORTHSTAR_ENERGY=0/1 (or a mode literal): the
                    # adiabatic A/B lever — 1 = adiabatic_v (docs/
                    # energy.md), the next healthy-chip A/B pair
                    energy=(None if os.environ.get(
                        "NORTHSTAR_ENERGY", "0") in ("0", "")
                        else "adiabatic_v"
                        if os.environ["NORTHSTAR_ENERGY"] == "1"
                        else os.environ["NORTHSTAR_ENERGY"]),
                    log=lambda m: print(m, file=sys.stderr, flush=True))
    out = os.environ.get("NORTHSTAR_OUT", os.path.join(REPO,
                                                       "NORTHSTAR.json"))
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
