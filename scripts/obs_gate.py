#!/usr/bin/env python
"""The perf-regression gate: band-check an obs report against a banked
baseline.

The first automated consumer of the PERF.md evidence format: instead
of a human reading ``obs_report.py --diff`` output, CI hands this
script a fresh obs report JSONL (``serve_bench.py --obs-out`` /
``serve.py --obs-out``) and a banked baseline JSON of per-metric
tolerance bands; every band renders as a pass/fail row and any
failure exits nonzero — the ``latency-gate`` CI job.

  python scripts/obs_gate.py \\
      --baseline tests/fixtures/serve_gate_baseline.json \\
      --report /tmp/serve_obs.jsonl

Baseline grammar (``br-obs-gate-v1``) — every section optional, every
leaf a band ``{"min": x, "max": y, "equals": z}`` (any subset)::

    {"schema": "br-obs-gate-v1",
     "description": "why these bands were chosen",
     "counters":   {"serve_failed": {"max": 0},
                    "serve_answered": {"equals": 30}},
     "histograms": {"serve_stage_seconds": {
                        "stage=total": {"count": {"min": 30},
                                        "p50_s": {"max": 2.0},
                                        "p99_s": {"max": 10.0}}}},
     "compile":    {"retraces": {"max": 0}},
     "spans":      {"solve": {"max": 60.0}}}

* **counters** check the report's counter dict, missing -> 0 (the
  ``obs.diff`` convention, so a never-exercised surface bands cleanly).
* **histograms** select one series per ``k=v[,k=v]`` label selector of
  a family (obs/counters.py HIST_KEYS) and band its ``count`` /
  ``sum_s`` / ``mean_s`` / ``p50_s`` / ``p90_s`` / ``p95_s`` /
  ``p99_s``; a MISSING series is empty — ``count`` bands see 0 and a
  quantile band fails loudly ("no observations"), which is exactly
  what a disappeared metric should do.
* **compile** bands the compile summary scalars (``compiles`` /
  ``retraces`` / ``cache_misses``...), missing -> 0.
* **spans** bands total wall seconds per span name.

Counters want exact-or-bounded bands; histogram quantiles want bands
loose enough to be non-flaky on shared CI runners (document the choice
in the baseline's ``description``).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GATE_SCHEMA = "br-obs-gate-v1"

_HIST_METRICS = ("count", "sum_s", "mean_s", "p50_s", "p90_s",
                 "p95_s", "p99_s")


def _check_band(value, band):
    """(ok, detail) for one value against ``{"min","max","equals"}``."""
    bad = sorted(set(band) - {"min", "max", "equals"})
    if bad:
        raise ValueError(f"unknown band key(s) {bad}; known: "
                         f"['equals', 'max', 'min']")
    if value is None:
        return False, "no observations"
    parts, ok = [], True
    if "equals" in band:
        good = value == band["equals"]
        ok &= good
        parts.append(f"== {band['equals']}")
    if "min" in band:
        good = value >= band["min"]
        ok &= good
        parts.append(f">= {band['min']}")
    if "max" in band:
        good = value <= band["max"]
        ok &= good
        parts.append(f"<= {band['max']}")
    return ok, " and ".join(parts) or "(empty band)"


def _parse_selector(sel):
    """``"stage=total,mech=h2o2"`` -> label dict ("" = unlabeled)."""
    labels = {}
    for part in str(sel).split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        if not eq or not k:
            raise ValueError(f"histogram selector {sel!r} wants "
                             f"k=v[,k=v] (or '' for unlabeled)")
        labels[k.strip()] = v.strip()
    return labels


def _hist_metric(ser, metric):
    from batchreactor_tpu.obs import counters as C

    if metric == "count":
        return ser["count"]
    if metric == "sum_s":
        return ser["sum"]
    if metric == "mean_s":
        return C.hist_mean(ser)
    if metric.startswith("p") and metric.endswith("_s"):
        return C.hist_quantile(ser, float(metric[1:-2]) / 100.0)
    raise ValueError(f"unknown histogram metric {metric!r}; known: "
                     f"{list(_HIST_METRICS)}")


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def run_gate(baseline, report):
    """Evaluate every band; returns ``(failures, lines)`` — the
    rendered pass/fail table and the failing rows."""
    from batchreactor_tpu.obs import counters as C

    if baseline.get("schema", GATE_SCHEMA) != GATE_SCHEMA:
        raise ValueError(f"unsupported gate schema "
                         f"{baseline.get('schema')!r} (this gate "
                         f"speaks {GATE_SCHEMA})")
    known = {"schema", "description", "counters", "histograms",
             "compile", "spans"}
    unknown = sorted(set(baseline) - known)
    if unknown:
        raise ValueError(f"unknown gate section(s) {unknown}; known: "
                         f"{sorted(known)}")
    lines, failures = [], []

    def row(ok, kind, name, value, detail):
        line = (f"  [{'ok' if ok else 'FAIL':>4s}] {kind} {name}: "
                f"{_fmt(value)} (want {detail})")
        lines.append(line)
        if not ok:
            failures.append(line)

    ctrs = report.get("counters") or {}
    for name, band in sorted((baseline.get("counters") or {}).items()):
        ok, detail = _check_band(ctrs.get(name) or 0, band)
        row(ok, "counter", name, ctrs.get(name) or 0, detail)

    hists = report.get("histograms") or {}
    for fam, selectors in sorted((baseline.get("histograms")
                                  or {}).items()):
        series = {tuple(sorted((ser.get("labels") or {}).items())): ser
                  for ser in hists.get(fam) or []}
        for sel, metrics in sorted(selectors.items()):
            labels = _parse_selector(sel)
            ser = series.get(tuple(sorted(labels.items())),
                             C.hist_new())
            name = fam + ("{" + sel + "}" if sel else "")
            for metric, band in sorted(metrics.items()):
                value = _hist_metric(ser, metric)
                ok, detail = _check_band(value, band)
                row(ok, "hist", f"{name} {metric}", value, detail)

    comp = report.get("compile") or {}
    for name, band in sorted((baseline.get("compile") or {}).items()):
        ok, detail = _check_band(comp.get(name) or 0, band)
        row(ok, "compile", name, comp.get(name) or 0, detail)

    span_totals = {}
    for s in report.get("spans") or []:
        if s.get("dur") is not None:
            span_totals[s["name"]] = (span_totals.get(s["name"], 0.0)
                                      + s["dur"])
    for name, band in sorted((baseline.get("spans") or {}).items()):
        ok, detail = _check_band(span_totals.get(name, 0.0), band)
        row(ok, "span", name, span_totals.get(name, 0.0), detail)

    return failures, lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", required=True,
                    help="banked tolerance-band JSON (br-obs-gate-v1)")
    ap.add_argument("--report", required=True,
                    help="candidate obs report JSONL")
    args = ap.parse_args(argv)

    from batchreactor_tpu import obs

    with open(args.baseline) as f:
        baseline = json.load(f)
    report = obs.read_jsonl(args.report)

    desc = baseline.get("description")
    print(f"obs gate [{GATE_SCHEMA}] baseline="
          f"{os.path.basename(args.baseline)}"
          + (f"\n  ({desc})" if desc else ""))
    failures, lines = run_gate(baseline, report)
    for line in lines:
        print(line)
    if failures:
        print(f"GATE FAILED: {len(failures)} band(s) out of tolerance",
              file=sys.stderr)
        for line in failures:
            print(line, file=sys.stderr)
        return 1
    print(f"gate passed ({len(lines)} bands)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
