"""Enumerate falloff-convention candidates against the two golden scalar
constraints:
  (A) reverse of H+CH3(+M)<=>CH4(+M) at 1173 K: k_rev = 1.1686e-3 1/s
      (pure-H-production channel at t=0, golden row 2)
  (B) forward of 2CH3(+M)<=>C2H6(+M) at 1173 K: k_fwd = 79.6 m^3/mol/s
      (golden C2H6 ~ k t^3 growth, method calibrated on CH3+O2 exact)
"""
import sys
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
import batchreactor_tpu as br
from batchreactor_tpu.ops import gas_kinetics as gk
from batchreactor_tpu.ops.thermo import gibbs_over_RT
from batchreactor_tpu.utils.constants import R

LIB = "/root/reference/test/lib"
gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
sp = list(gm.species)
eqs = list(gm.equations)
i_ch4 = next(i for i, e in enumerate(eqs) if "H+CH3(+M)" in e)
i_c2h6 = next(i for i, e in enumerate(eqs) if "C2H6" in e and "2CH3" in e)
print("rxns:", eqs[i_ch4], "|", eqs[i_c2h6])

T = 1173.0
x = np.zeros(len(sp)); x[sp.index("CH4")], x[sp.index("O2")], x[sp.index("N2")] = .25, .5, .25
conc = jnp.asarray(x * 1e5 / (R * T))
kinf = np.asarray(gk._arrhenius(T, gm.log_A, gm.beta, gm.Ea))
k0 = np.asarray(gk._arrhenius(T, gm.log_A0, gm.beta0, gm.Ea0))
cM = np.asarray(gm.eff @ conc)          # SI mol/m^3 incl. efficiencies
cMc = cM * 1e-6                          # "cgs-valued" collider conc
Pr = k0 / np.maximum(kinf, 1e-300) * cM
L = Pr / (1 + Pr)
F = np.asarray(gk._troe_F(jnp.asarray(T), jnp.asarray(Pr), gm.troe, gm.has_troe))
# Pr variants
Pr_cgs = k0 / np.maximum(kinf, 1e-300) * cMc    # cM mistakenly in mol/cm3
L_cgs = Pr_cgs / (1 + Pr_cgs)
F_cgs = np.asarray(gk._troe_F(jnp.asarray(T), jnp.asarray(Pr_cgs), gm.troe, gm.has_troe))

g = np.asarray(gibbs_over_RT(T, th))
dnu = np.asarray(gm.nu_r - gm.nu_f)
dG = dnu @ g
dn = dnu.sum(axis=1)

KF = {
  "kinf*L*F(phys)": kinf * L * F,
  "kinf": kinf,
  "kinf*L": kinf * L,
  "kinf*F": kinf * F,
  "kinf*Lc*Fc": kinf * L_cgs * F_cgs,
  "kinf*Lc": kinf * L_cgs,
  "k0*cM*L*F": k0 * cM * L * F,
  "k0*cMc*L*F": k0 * cMc * L * F,
  "k0*cM": k0 * cM,
  "k0*cMc": k0 * cMc,
  "k0": k0,
  "kinf*cM*L*F": kinf * cM * L * F,
  "kinf*cMc*L*F": kinf * cMc * L * F,
  "kinf*cMc*L": kinf * cMc * L,
  "kinf*cMc*F": kinf * cMc * F,
  "kinf*cMc": kinf * cMc,
  "kinf*cM": kinf * cM,
}
lc_atm = np.log(101325.0 / (R * T)); lc_bar = np.log(1e5 / (R * T))
KC = {
  "atm(phys)": -dG + dn * lc_atm,
  "bar": -dG + dn * lc_bar,
  "bar*1e6(quirk)": -dG + dn * (lc_bar + np.log(1e6)),
  "bar/1e6": -dG + dn * (lc_bar - np.log(1e6)),
  "Kp": -dG,
}
tA, tB = 1.1686e-3, 79.6
print(f"\n(B) forward C2H6 target {tB:.4g}; candidate / target ratios:")
for n, v in KF.items():
    r = v[i_c2h6] / tB
    flag = " <== " if 0.97 < r < 1.03 else ""
    print(f"  {n:>16}: {v[i_c2h6]:.4e}  ratio {r:.4g}{flag}")
print(f"\n(A) reverse CH4 target {tA:.4g}; ratios for each kf/Kc combo:")
for nk, v in KF.items():
    row = []
    for nc, kc in KC.items():
        kr = v[i_ch4] * np.exp(-kc[i_ch4])
        r = kr / tA
        row.append(f"{nc}:{r:.3g}")
        if 0.97 < r < 1.03:
            print(f"  MATCH {nk} / {nc}: k_rev={kr:.4e} ratio {r:.4f}")
    print(f"  {nk:>16}: " + "  ".join(row))
