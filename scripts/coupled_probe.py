"""Coupled gas+surface TPU throughput probe: the batch_gas_and_surf workload.

The flagship coupled configuration (/root/reference/test/batch_gas_and_surf/
batch.xml: GRI-Mech 3.0 gas + CH4-on-Ni surface, CH4/O2/N2 = 0.25/0.5/0.25,
1173 K, 1 bar, 10 s) widened to a B-lane temperature sweep through the
high-level ``batch_reactor_sweep`` coupled mode (gmd= + smd=) with the
variable-order BDF solver — the mode the reference's programmatic form
cannot express at all, and its file form runs one condition per process.

Reports conditions/sec and cross-checks final gas states on a few lanes
against the independent native C++ BDF (``native.solve_surf_bdf`` with
gm=), writing COUPLED_{DEVICE}.json (COUPLED_TPU.json on the chip,
COUPLED_CPU.json on a CPU-pinned run).

Usage:  python scripts/coupled_probe.py          # B=64 on the default device
        CP_B=16 CP_T1=1.0 python scripts/coupled_probe.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
os.environ.setdefault("BR_EXP32", "1")  # the bench protocol

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")


def main():
    import jax

    if os.environ.get("CP_EFFORT"):
        # global XLA scheduling-effort knob (applies to every jit in this
        # process): -1.0 skips the expensive late optimization passes — an
        # escape hatch for the coupled compile wall worth a try before the
        # structural fallbacks (fwd/remat Jacobians)
        jax.config.update("jax_exec_time_optimization_effort",
                          float(os.environ["CP_EFFORT"]))
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.models.surface import compile_mech
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors
    from batchreactor_tpu.solver.sdirk import SUCCESS
    from batchreactor_tpu.utils.profiling import Phases

    B = int(os.environ.get("CP_B", "64"))
    t1 = float(os.environ.get("CP_T1", "10.0"))
    # CP_JAC selects the Jacobian mode: analytic (closed form), fwd
    # (jax.jacfwd fallback), or remat (closed form under jax.checkpoint) —
    # the escape hatches for the coupled analytic-J TPU compile wall
    cp_jac = os.environ.get("CP_JAC", "analytic")
    if cp_jac not in ("analytic", "fwd", "remat"):
        raise SystemExit(f"CP_JAC must be 'analytic', 'fwd' or 'remat', "
                         f"got {cp_jac!r}")
    analytic = {"analytic": True, "fwd": False, "remat": "remat"}[cp_jac]
    # the bench protocol's Jacobian window (PERF.md); CP_JW=1 reverts
    jw = int(os.environ.get("CP_JW", "8"))
    Asv = 1.0  # reference batch.xml has no <Asv>; the parser defaults to 1
    ph = Phases()
    with ph("parse"):
        # grimech.dat + ch4ni.xml ship in tests/fixtures too (vendored), so
        # this runs on bare clones via the LIB fallback
        gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
        th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
        sm = compile_mech(f"{LIB}/ch4ni.xml", th, list(gm.species))
    surf_xml = "ch4ni.xml"
    T_grid = jnp.linspace(1073.0, 1273.0, B)

    t0 = time.perf_counter()
    with ph("solve_incl_compile"):
        out = br.batch_reactor_sweep(
            {"CH4": 0.25, "O2": 0.5, "N2": 0.25}, T_grid, 1e5, t1,
            chem=br.Chemistry(surfchem=True, gaschem=True),
            thermo_obj=th, gmd=gm, smd=sm, Asv=Asv,
            method="bdf", segment_steps=512, analytic_jac=analytic,
            jac_window=jw)
    warm = time.perf_counter() - t0
    # second run = steady-state timing (compile cached)
    t0 = time.perf_counter()
    with ph("solve"):
        out = br.batch_reactor_sweep(
            {"CH4": 0.25, "O2": 0.5, "N2": 0.25}, T_grid, 1e5, t1,
            chem=br.Chemistry(surfchem=True, gaschem=True),
            thermo_obj=th, gmd=gm, smd=sm, Asv=Asv,
            method="bdf", segment_steps=512, analytic_jac=analytic,
            jac_window=jw)
    wall = time.perf_counter() - t0
    n_ok = int((out["status"] == SUCCESS).sum())

    # ---- final-state parity vs the independent native C++ BDF ------------
    spot = []
    with ph("spot_check"):
        from batchreactor_tpu import native

        X = np.zeros(len(th.species))
        sp = list(th.species)
        X[sp.index("CH4")], X[sp.index("O2")], X[sp.index("N2")] = .25, .5, .25
        for b in np.linspace(0, B - 1, 4).astype(int):
            if int(out["status"][b]) != SUCCESS:
                # a failed lane's final state is partial — that is a solve
                # failure to report, not a parity error to measure
                spot.append({"lane": int(b), "T": float(T_grid[b]),
                             "skipped": "lane status != SUCCESS"})
                continue
            y0 = np.asarray(sweep_solution_vectors(
                jnp.asarray(X)[None, :], th.molwt,
                T_grid[b][None], 1e5, ini_covg=sm.ini_covg)[0])
            rn = native.solve_surf_bdf(sm, th, float(T_grid[b]), Asv, y0,
                                       0.0, t1, gm=gm, rtol=1e-6, atol=1e-10)
            ng = len(sp)
            moles = rn.y[:ng] / np.asarray(th.molwt)
            x_nat = moles / moles.sum()
            # compare bulk species (mole fraction > 1e-8) relative
            x_tpu = np.array([out["x"][s][b] for s in sp])
            mask = x_nat > 1e-8
            rel = float(np.max(np.abs(x_tpu[mask] - x_nat[mask])
                               / x_nat[mask]))
            spot.append({"lane": int(b), "T": float(T_grid[b]),
                         "max_rel_err_bulk_x": rel})

    rec = {
        "workload": f"GRI30 + {surf_xml} coupled, CH4/O2/N2 0.25/0.5/0.25, "
                    f"1 bar, Asv={Asv}, t1={t1}, B={B} T-sweep "
                    f"1073-1273 K, rtol 1e-6 atol 1e-10",
        "method": "bdf", "B": B, "analytic_jac": analytic,
        "jac_window": jw,
        "xla_effort": float(os.environ.get("CP_EFFORT", "0")),
        "wall_s": round(wall, 2), "cond_per_s": round(B / wall, 3),
        "warm_s": round(warm, 1),
        "device": jax.default_backend(),
        "n_ok": n_ok,
        "x_parity_native": spot,
        "phases_s": {k: round(v, 2) for k, v in ph.summary().items()},
    }
    out_path = os.environ.get(
        "CP_OUT", os.path.join(REPO, f"COUPLED_{rec['device'].upper()}.json"))
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
