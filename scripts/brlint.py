#!/usr/bin/env python
"""brlint CLI shim: JAX tracer-safety static analysis for this repo.

  python scripts/brlint.py batchreactor_tpu/            # tier-A AST scan
  python scripts/brlint.py --jaxpr                      # tier-B jaxpr audit
  python scripts/brlint.py --tier C --json              # tier C: contracts
                                                        #   + concurrency
  python scripts/brlint.py --tier D --json              # tier C + budgets
  python scripts/brlint.py --concurrency                # host-race lint only
  python scripts/brlint.py batchreactor_tpu/ --baseline brlint_baseline.json

Exit-code contract (regression-tested in tests/test_analysis.py; the
CI gates key off it): 0 = clean, 1 = findings, 2 = usage error — with
``--json`` exactly as without, and a crashed lint exits nonzero via
the uncaught exception rather than printing an empty findings list.

The implementation lives in batchreactor_tpu/analysis/ (rule catalogue and
suppression policy: docs/development.md).  Tier A and the concurrency lint
are stdlib-only AST scans and must stay runnable on a host with no (or a
broken/wedged) jax install — so this shim loads the analysis subpackage
through a lightweight namespace parent instead of the real
``batchreactor_tpu/__init__``, which imports jax and the full solver stack
at module scope.  The traced tiers (--jaxpr / --contracts) import jax
lazily inside the contract engine and should run under JAX_PLATFORMS=cpu
in CI.
"""

import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# lightweight parent package: gives `batchreactor_tpu.analysis.*` (and, for
# --jaxpr, the models/ops/solver subpackages via their relative imports) an
# import path WITHOUT executing batchreactor_tpu/__init__.py — the real
# init imports jax + api at module scope, which tier A must not pay (and
# which fails outright where jax is absent).  setdefault: a process that
# already imported the real package keeps it.
_pkg = types.ModuleType("batchreactor_tpu")
_pkg.__path__ = [os.path.join(REPO, "batchreactor_tpu")]
sys.modules.setdefault("batchreactor_tpu", _pkg)

from batchreactor_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
