"""Capture a jax.profiler device trace of a bench segment (round-4 task:
direct evidence for the attempt-cost decomposition that round 3 could only
infer from lever deltas, PERF.md).

Runs the bench workload's segmented BDF sweep (GRI-3.0, B lanes) with the
bench-protocol configuration, warms one segment (compile excluded), then
traces a handful of steady-state segments with ``jax.profiler.trace``.
The xplane trace lands in ``perf_trace/<ts>/`` and — when the
tensorboard_plugin_profile toolchain is importable — is immediately
digested into TRACE_SUMMARY.json: top self-time ops from the device
op-profile, the per-category split (the data PERF.md's findings paragraph
cites).

Wedge-safe usage (the capture touches the chip — background + SIGTERM):
  timeout -s TERM -k 45 1800 python scripts/trace_capture.py
  TC_B=256 TC_SEGMENTS=4 TC_CPU=1 ... (CPU control run)
"""

import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
os.environ.setdefault("BR_EXP32", "1")

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")


def _analyze(log_dir):
    """Run _analyze_inproc in a child: the profile toolchain's generated
    protos need PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python, which must
    be set before ANY protobuf import — impossible in a process that has
    already initialized jax/tensorflow."""
    import subprocess

    env = {**os.environ,
           "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION": "python",
           "TC_ANALYZE": log_dir}
    try:
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, capture_output=True, text=True,
                             timeout=600)
    except subprocess.TimeoutExpired:
        return {"error": "analysis subprocess timed out"}
    for line in (out.stdout or "").splitlines():
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": f"analysis subprocess rc={out.returncode}: "
                     f"{(out.stderr or '')[-500:]}"}


def _analyze_inproc(log_dir):
    """xplane.pb -> {top ops by self time, category split}."""
    try:
        from xprof.convert import raw_to_tool_data
    except Exception:
        try:
            from tensorboard_plugin_profile.convert import raw_to_tool_data
        except Exception as e:  # pragma: no cover - toolchain optional
            return {"error": f"profile toolchain unavailable: {e}"}
    xplanes = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                        recursive=True)
    if not xplanes:
        return {"error": "no xplane.pb captured"}
    try:
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            xplanes, "op_profile", {})
        if isinstance(data, bytes):
            data = data.decode()
        op = json.loads(data)
    except Exception as e:
        return {"error": f"op_profile conversion failed: {e}",
                "xplane_files": xplanes}

    out = {"xplane_files": xplanes, "device_type": op.get("deviceType")}

    def _self_ps(m):
        v = m.get("selfTimePs", m.get("self_time_ps", 0))
        return float(v or 0)

    # op_profile tree shapes vary by backend/version: byCategory (TPU) or
    # byProgram; descend to the deepest nodes and aggregate self time
    root = None
    for key in ("byCategoryExcludeIdle", "byCategory",
                "byProgramExcludeIdle", "byProgram"):
        node = op.get(key)
        if node and node.get("children"):
            root = node
            out["tree"] = key
            break
    if root is None:
        out["parse_error"] = "no populated op-profile tree"
        out["raw_keys"] = list(op.keys())
        return out

    leaves = []

    def walk(node, path):
        kids = node.get("children") or []
        m = node.get("metrics") or {}
        if not kids:
            if _self_ps(m):
                leaves.append({"op": node.get("name"),
                               "path": "/".join(path[-2:]),
                               "self_time_ps": _self_ps(m)})
            return
        for c in kids:
            walk(c, path + [node.get("name") or ""])

    walk(root, [])
    total = sum(o["self_time_ps"] for o in leaves) or 1.0
    leaves.sort(key=lambda o: -o["self_time_ps"])
    for o in leaves:
        o["self_frac"] = round(o["self_time_ps"] / total, 4)
    out["total_self_time_ps"] = total
    out["n_leaf_ops"] = len(leaves)
    out["top_ops"] = leaves[:25]
    return out


def main():
    if os.environ.get("TC_ANALYZE"):  # child mode: parse-only, no jax
        print(json.dumps(_analyze_inproc(os.environ["TC_ANALYZE"])))
        return

    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("TC_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")

    import batchreactor_tpu as br
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors
    from batchreactor_tpu.parallel.sweep import ensemble_solve_segmented

    B = int(os.environ.get("TC_B", "256"))
    seg = int(os.environ.get("TC_SEG", "256"))
    n_traced = int(os.environ.get("TC_SEGMENTS", "4"))
    jw = int(os.environ.get("TC_JW", "8"))
    log = lambda m: print(m, file=sys.stderr, flush=True)

    gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sp = list(gm.species)
    X = np.zeros(len(sp))
    X[sp.index("CH4")], X[sp.index("O2")], X[sp.index("N2")] = .25, .5, .25
    T = jnp.linspace(1500.0, 2000.0, B)
    y0s = sweep_solution_vectors(jnp.broadcast_to(jnp.asarray(X),
                                                  (B, len(sp))),
                                 th.molwt, T, 1e5)
    rhs, jacf = make_gas_rhs(gm, th), make_gas_jac(gm, th)
    kw = dict(rtol=1e-6, atol=1e-10, jac=jacf, method="bdf", jac_window=jw)

    # warm run: pays compile, fills the executable cache; sized so the
    # traced run below replays the exact same program shape
    log(f"[trace] warm run B={B} seg={seg} (compile)...")
    t0 = time.perf_counter()
    res = ensemble_solve_segmented(rhs, y0s, 0.0, 8e-4, {"T": T},
                                   segment_steps=seg,
                                   max_segments=2, max_attempts=2 * seg)
    jax.block_until_ready(res.y)
    log(f"[trace] warm done in {time.perf_counter() - t0:.1f}s")

    ts = time.strftime("%Y%m%d_%H%M%S")
    log_dir = os.path.join(REPO, "perf_trace", ts)
    os.makedirs(log_dir, exist_ok=True)
    log(f"[trace] tracing {n_traced} segments -> {log_dir}")
    t0 = time.perf_counter()
    with jax.profiler.trace(log_dir):
        res = ensemble_solve_segmented(rhs, y0s, 0.0, 8e-4, {"T": T},
                                       segment_steps=seg,
                                       max_segments=n_traced,
                                       max_attempts=n_traced * seg)
        jax.block_until_ready(res.y)
    wall = time.perf_counter() - t0
    log(f"[trace] traced window: {wall:.1f}s")

    summary = {
        "backend": jax.default_backend(),
        "B": B, "segment_steps": seg, "n_segments": n_traced,
        "jac_window": jw,
        "traced_wall_s": round(wall, 2),
        "log_dir": os.path.relpath(log_dir, REPO),
        "analysis": _analyze(log_dir),
    }
    out = os.environ.get("TC_OUT", os.path.join(REPO, "TRACE_SUMMARY.json"))
    with open(out, "w") as fh:
        json.dump(summary, fh, indent=1)
    print(json.dumps({k: v for k, v in summary.items() if k != "analysis"}))
    an = summary["analysis"]
    if isinstance(an, dict) and an.get("top_ops"):
        for o in an["top_ops"][:10]:
            print(f"  {o['self_frac']:6.1%}  {o['category']:<28} {o['op']}")


if __name__ == "__main__":
    main()
