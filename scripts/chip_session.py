"""One-shot orchestrator for a healthy-chip window (round-5 ordering).

The tunneled chip wedges for hours at a time (PERF.md), so when it IS
healthy every deliverable must run in one supervised pass, banking results
incrementally.  ROUND-4 LESSON (VERDICT r4 weak #1): compile probes are
the wedge vector — a SIGTERM'd mid-compile axon client wedged the tunnel
at step 2 of 8 and sacrificed the other six deliverables, and every probe
stage after the first timeout measured a wedged chip, not the program.
So round 5 runs strictly safest-first, re-probes after EVERY step, and
puts the wedge-prone compile work DEAD LAST:

  1. bench      — live rung ladder (bench.py banks each healthy rung)
  2. northstar  — 4096-lane map, chunk-512 instrumented + chunk-4096 A/B
  3. smoke      — on-chip pytest tier (scripts/tpu_smoke.py)
  4. trace      — device trace of a bench segment (scripts/trace_capture.py)
  5. invbudget  — amortized Newton-linear-algebra construction budget
  6. coupled    — the PRODUCT attempt (scripts/coupled_probe.py ->
                  COUPLED_TPU.json): analytic J on the round-5 round-trip-
                  free RHS structure; on timeout, one retry at XLA
                  exec_time_optimization_effort=-1.0 (probe between)
  7. compile    — diagnostic localization ladder, ONLY reached if the
                  chip is still healthy; aborts at the first timed-out
                  stage (later stages would measure the wedge, not the
                  program)

Usage (ALWAYS as a background task):
  python scripts/chip_session.py                 # all steps
  CS_STEPS=bench,coupled python scripts/chip_session.py
Writes CHIP_SESSION.json progress after every step.
"""

import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "CHIP_SESSION.json")

# SIGTERM-with-grace teardown now lives in the library (resilience/
# guard.py, stdlib-only by design) — loaded straight from its file so
# this orchestrator keeps its no-jax-import guarantee (a wedged chip
# must not be able to hang the supervisor)
_spec = importlib.util.spec_from_file_location(
    "_br_resilience_guard",
    os.path.join(REPO, "batchreactor_tpu", "resilience", "guard.py"))
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)
run_guarded = _guard.run_guarded


def run(cmd, timeout, extra_env=None, label=""):
    env = {**os.environ, **(extra_env or {})}
    print(f"=== {label or cmd}: start (timeout {timeout}s)",
          file=sys.stderr, flush=True)
    r = run_guarded(cmd, timeout, env=env, cwd=REPO, merge_stderr=True)
    print((r.stdout or "")[-1500:], file=sys.stderr, flush=True)
    print(f"=== {label}: rc={r.rc} timed_out={r.timed_out} "
          f"{r.wall_s:.0f}s", file=sys.stderr, flush=True)
    return {"label": label, "rc": r.rc, "timed_out": r.timed_out,
            "wall_s": round(r.wall_s, 1), "tail": (r.stdout or "")[-1200:]}


def probe():
    r = run([sys.executable, os.path.join(REPO, "bench.py")], 240,
            {"BENCH_MODE": "probe"}, "probe")
    return r["rc"] == 0 and not r["timed_out"]


def main():
    known = ["bench", "northstar", "smoke", "trace", "invbudget",
             "coupled", "compile"]
    if os.environ.get("CS_STEPS"):
        steps = [s.strip() for s in os.environ["CS_STEPS"].split(",")
                 if s.strip()]
        unknown = [s for s in steps if s not in known]
        if unknown:
            raise SystemExit(f"unknown CS_STEPS {unknown}; known: {known}")
    else:
        steps = known
    state = {"t_start": time.strftime("%H:%M:%S"), "steps": []}

    def record(rec):
        state["steps"].append(rec)
        with open(OUT, "w") as fh:
            json.dump(state, fh, indent=1)

    if not probe():
        record({"label": "probe", "rc": 1,
                "note": "chip unreachable at session start"})
        return 1

    py = sys.executable
    if "bench" in steps:
        # +1024 over the default ladder: bench scaling was only ever
        # measured flat to B=512; the map A/B (northstar step) wants to
        # know whether bigger single launches keep the per-lane rate
        # 5 rungs x 1500 s worst-case rung timeout + probes: the wrapper
        # budget must exceed the sum or the B=1024 rung (added for the
        # scaling question) gets killed mid-compile — and a killed TPU
        # client wedges the tunnel
        record(run([py, os.path.join(REPO, "bench.py")], 9000,
                   {"BENCH_LADDER": "64,128,256,512,1024"},
                   "bench-ladder"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after bench"})
            return 1
    if "northstar" in steps:
        record(run([py, "scripts/northstar_sweep.py"], 3600,
                   {"NORTHSTAR_CKPT": "/tmp/ns_chip512",
                    "NORTHSTAR_OUT": os.path.join(REPO,
                                                  "NORTHSTAR_TPU.json")},
                   "northstar-chunk512"))
        # A/B: the whole map as ONE chunk — no checkpoint halo
        record(run([py, "scripts/northstar_sweep.py"], 3600,
                   {"NORTHSTAR_CKPT": "/tmp/ns_chip4096",
                    "NORTHSTAR_CHUNK": "4096",
                    "NORTHSTAR_OUT": os.path.join(
                        REPO, "NORTHSTAR_TPU_1CHUNK.json")},
                   "northstar-chunk4096"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after northstar"})
            return 1
    if "smoke" in steps:
        record(run([py, "scripts/tpu_smoke.py"], 2700, {},
                   "tpu-smoke-tier"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after smoke"})
            return 1
    if "trace" in steps:
        record(run([py, "scripts/trace_capture.py"], 1800, {},
                   "trace-capture"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after trace"})
            return 1
    if "invbudget" in steps:
        record(run([py, "scripts/inv_budget.py"], 1500, {},
                   "inv-budget"))
        if not probe():
            record({"label": "abort",
                    "note": "chip wedged after invbudget"})
            return 1
    if "coupled" in steps:
        # PRODUCT attempt first (VERDICT r4: the diagnostic ladder wedged
        # the chip before the product ever ran).  The round-5 RHS structure
        # has no mole-frac/pressure round-trip — the prime structural
        # suspect — so analytic J at the bench-protocol jw=8 is the right
        # first try; the 3000 s budget covers the round-3 observed 30-58
        # min walls becoming a finite-but-slow compile.
        rec = run([py, "scripts/coupled_probe.py"], 3000,
                  {"CP_JAC": "analytic",
                   "CP_OUT": os.path.join(REPO, "COUPLED_TPU.json")},
                  "coupled-product-analytic")
        record(rec)
        if not probe():
            record({"label": "abort", "note": "chip wedged after coupled"})
            return 1
        if rec["rc"] != 0 or rec["timed_out"]:
            # one retry with the global XLA effort knob lowered — skips the
            # expensive late optimization passes
            rec = run([py, "scripts/coupled_probe.py"], 3000,
                      {"CP_JAC": "analytic", "CP_EFFORT": "-1.0",
                       "CP_OUT": os.path.join(REPO, "COUPLED_TPU.json")},
                      "coupled-product-loweffort")
            record(rec)
            if not probe():
                record({"label": "abort",
                        "note": "chip wedged after coupled retry"})
                return 1
    if "compile" in steps:
        # dead last, diagnostic only: CCP_ABORT_ON_TIMEOUT stops the ladder
        # at the first timed-out stage — every later stage would measure
        # the wedge the timeout likely caused, not the program (that is
        # exactly how round 4 burned six deliverables)
        record(run([py, "scripts/coupled_compile_probe.py"], 4800,
                   {"CCP_TIMEOUT": "420", "CCP_ABORT_ON_TIMEOUT": "1"},
                   "coupled-compile-ladder"))
    record({"label": "done", "chip_healthy_at_end": probe()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
