"""One-shot orchestrator for a healthy-chip window (round-4 deliverables).

The tunneled chip wedges for hours at a time (PERF.md), so when it IS
healthy every deliverable must run in one supervised pass, banking results
incrementally.  Steps, in priority order (each its own subprocess with a
SIGTERM-first timeout; a mid-session wedge stops the ladder but keeps
everything already banked):

  1. bench      — live rung ladder (bench.py banks each healthy rung)
  2. compile    — coupled compile-wall localization ladder
                  (scripts/coupled_compile_probe.py -> COMPILE_PROBE.json)
  3. coupled    — coupled gas+surf TPU throughput (scripts/coupled_probe.py
                  -> COUPLED_TPU.json) with the Jacobian mode the ladder
                  proved: analytic (s5 ok) > remat at jw=1 (s7 ok) >
                  jacfwd (s4 ok) > skipped (nothing compiles)
  4. northstar  — 4096-lane map, chunk-512 instrumented + chunk-4096 A/B
  5. smoke      — on-chip pytest tier (scripts/tpu_smoke.py)
  6. trace      — device trace of a bench segment (scripts/trace_capture.py)
  7. invbudget  — amortized Newton-linear-algebra construction budget
                  (scripts/inv_budget.py -> INV_BUDGET.json)

Usage (ALWAYS as a background task):
  python scripts/chip_session.py                 # all steps
  CS_STEPS=bench,coupled python scripts/chip_session.py
Writes CHIP_SESSION.json progress after every step.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "CHIP_SESSION.json")


def run(cmd, timeout, extra_env=None, label=""):
    env = {**os.environ, **(extra_env or {})}
    t0 = time.time()
    print(f"=== {label or cmd}: start (timeout {timeout}s)",
          file=sys.stderr, flush=True)
    proc = subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
        timed_out = False
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=45)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        timed_out = True
    wall = time.time() - t0
    print((out or "")[-1500:], file=sys.stderr, flush=True)
    print(f"=== {label}: rc={proc.returncode} timed_out={timed_out} "
          f"{wall:.0f}s", file=sys.stderr, flush=True)
    return {"label": label, "rc": proc.returncode, "timed_out": timed_out,
            "wall_s": round(wall, 1), "tail": (out or "")[-1200:]}


def probe():
    r = run([sys.executable, os.path.join(REPO, "bench.py")], 240,
            {"BENCH_MODE": "probe"}, "probe")
    return r["rc"] == 0 and not r["timed_out"]


def main():
    known = ["bench", "compile", "coupled", "northstar", "smoke", "trace",
             "invbudget"]
    if os.environ.get("CS_STEPS"):
        steps = [s.strip() for s in os.environ["CS_STEPS"].split(",")
                 if s.strip()]
        unknown = [s for s in steps if s not in known]
        if unknown:
            raise SystemExit(f"unknown CS_STEPS {unknown}; known: {known}")
    else:
        steps = known
    state = {"t_start": time.strftime("%H:%M:%S"), "steps": []}

    def record(rec):
        state["steps"].append(rec)
        with open(OUT, "w") as fh:
            json.dump(state, fh, indent=1)

    if not probe():
        record({"label": "probe", "rc": 1,
                "note": "chip unreachable at session start"})
        return 1

    py = sys.executable
    if "bench" in steps:
        # +1024 over the default ladder: bench scaling was only ever
        # measured flat to B=512; the map A/B (northstar step) wants to
        # know whether bigger single launches keep the per-lane rate
        # 5 rungs x 1500 s worst-case rung timeout + probes: the wrapper
        # budget must exceed the sum or the B=1024 rung (added for the
        # scaling question) gets killed mid-compile — and a killed TPU
        # client wedges the tunnel
        record(run([py, os.path.join(REPO, "bench.py")], 9000,
                   {"BENCH_LADDER": "64,128,256,512,1024"},
                   "bench-ladder"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after bench"})
            return 1
    if "compile" in steps:
        record(run([py, "scripts/coupled_compile_probe.py"], 6000,
                   {"CCP_TIMEOUT": "600"}, "coupled-compile-ladder"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after compile"})
            return 1
    if "coupled" in steps:
        # choose the Jacobian mode the compile ladder proved out; with no
        # evidence (ladder skipped/failed) prefer the jacfwd fallback —
        # the analytic mode is the KNOWN compile wall (PERF.md), so
        # defaulting to it would burn the healthy-chip window re-failing
        cp_jac, skip = "fwd", False
        try:
            with open(os.path.join(REPO, "COMPILE_PROBE.json")) as fh:
                stages = {s["stage"]: s for s in json.load(fh)["stages"]}
            if stages.get("s5_bdf_ana", {}).get("ok"):
                cp_jac = "analytic"
            elif stages.get("s7_bdf_remat", {}).get("ok"):
                cp_jac = "remat"
            elif not stages.get("s4_bdf_fwd", {}).get("ok") and stages:
                skip = True  # nothing it can run compiles; don't burn time
        except (OSError, KeyError, json.JSONDecodeError):
            pass
        if skip:
            record({"label": "coupled-probe", "skipped":
                    "no coupled variant compiled in COMPILE_PROBE.json"})
        else:
            env = {"CP_JAC": cp_jac,
                   "CP_OUT": os.path.join(REPO, "COUPLED_TPU.json")}
            if cp_jac == "remat":
                # the ladder validated remat at jac_window=1 (stage s7);
                # run the exact program structure that compiled, not an
                # unproven remat+jw8 variant
                env["CP_JW"] = "1"
            record(run([py, "scripts/coupled_probe.py"], 5400, env,
                       f"coupled-probe-{cp_jac}"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after coupled"})
            return 1
    if "northstar" in steps:
        record(run([py, "scripts/northstar_sweep.py"], 3600,
                   {"NORTHSTAR_CKPT": "/tmp/ns_chip512",
                    "NORTHSTAR_OUT": os.path.join(REPO,
                                                  "NORTHSTAR_TPU.json")},
                   "northstar-chunk512"))
        # A/B: the whole map as ONE chunk — no checkpoint halo
        record(run([py, "scripts/northstar_sweep.py"], 3600,
                   {"NORTHSTAR_CKPT": "/tmp/ns_chip4096",
                    "NORTHSTAR_CHUNK": "4096",
                    "NORTHSTAR_OUT": os.path.join(
                        REPO, "NORTHSTAR_TPU_1CHUNK.json")},
                   "northstar-chunk4096"))
        if not probe():
            record({"label": "abort", "note": "chip wedged after northstar"})
            return 1
    if "smoke" in steps:
        record(run([py, "scripts/tpu_smoke.py"], 2700, {},
                   "tpu-smoke-tier"))
    if "trace" in steps:
        record(run([py, "scripts/trace_capture.py"], 1800, {},
                   "trace-capture"))
    if "invbudget" in steps:
        record(run([py, "scripts/inv_budget.py"], 1500, {},
                   "inv-budget"))
    record({"label": "done", "chip_healthy_at_end": probe()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
