"""Measure solver-variant throughput on the live accelerator.

Round-3 analysis (PERF.md): a batched step attempt runs far below compute
limits — the candidate levers are kernel-count and f64-emulation
reductions.  This probe measures them head-to-head on the bench workload
(GRI ignition sweep, B=128 by default, t1=8e-4 s, rtol 1e-6 / atol
1e-10), each variant in its own subprocess via bench.py's rung mode.
The VARIANTS table below is the authoritative list: SDIRK levers (Newton
refinement, f32 exponentials, Jacobian window, Newton tolerance), the
BDF solver against the same lever matrix, and the adopted accelerator
default stack (bdf + exp32 + inv32f + jac_window=8).

Correctness gate: every variant's per-lane ignition delays must match the
base variant (max rel diff reported; < 1e-3 expected — the measured lever
shifts are ~2.5e-5 at worst, PERF.md).  Results land in PERF_PROBE.json.

Run only on a healthy chip (the probe pre-flights like bench.py).
"""

import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
OUT = os.path.join(REPO, "PERF_PROBE.json")

# THE SIGTERM-with-grace rule lives in resilience/guard.py (stdlib-only);
# loaded from its file so this orchestrator never imports jax
_spec = importlib.util.spec_from_file_location(
    "_br_resilience_guard",
    os.path.join(REPO, "batchreactor_tpu", "resilience", "guard.py"))
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)
run_guarded = _guard.run_guarded

# every variant pins BENCH_METHOD, BR_EXP32 and BENCH_LINSOLVE explicitly:
# bench.py's rung mode now DEFAULTS to the winning config (method=bdf,
# BR_EXP32=1, linsolve auto -> inv32f on accelerators for BDF), so an
# unpinned variant would silently measure the lever it claims to isolate
VARIANTS = {
    "base": {"BENCH_METHOD": "sdirk", "BR_EXP32": "0",
             "BENCH_LINSOLVE": "inv32"},
    "nr": {"BENCH_METHOD": "sdirk", "BR_EXP32": "0",
           "BENCH_LINSOLVE": "inv32nr"},
    "exp32": {"BENCH_METHOD": "sdirk", "BR_EXP32": "1",
              "BENCH_LINSOLVE": "inv32"},
    "exp32nr": {"BENCH_METHOD": "sdirk", "BENCH_LINSOLVE": "inv32nr",
                "BR_EXP32": "1"},
    # Jacobian held for 4 step attempts (CVODE's quasi-constant iteration
    # matrix economy; M/inverse stay h-correct every attempt)
    "jw4": {"BENCH_METHOD": "sdirk", "BR_EXP32": "0",
            "BENCH_LINSOLVE": "inv32", "BENCH_JAC_WINDOW": "4"},
    # looser Newton displacement tolerance (CVODE uses ~0.1-0.33)
    "nt01": {"BENCH_METHOD": "sdirk", "BR_EXP32": "0",
             "BENCH_LINSOLVE": "inv32", "BENCH_NEWTON_TOL": "0.1"},
    # the full sdirk stack
    "all": {"BENCH_METHOD": "sdirk", "BENCH_LINSOLVE": "inv32nr",
            "BR_EXP32": "1", "BENCH_JAC_WINDOW": "4",
            "BENCH_NEWTON_TOL": "0.1"},
    # variable-order BDF (solver/bdf.py): ~2.6x fewer steps and 1 Newton
    # solve per step vs SDIRK4's five — measured 6x on CPU, and the
    # measured lever matrix on TPU (PERF.md): inv32nr +18% bit-identical,
    # exp32 +1.6% at 4.4e-5 tau shift
    # bdf variants pin BENCH_JAC_WINDOW too: the bench's own bdf default
    # is now jac_window=8, which would silently leak into these baselines
    "bdf": {"BENCH_METHOD": "bdf", "BR_EXP32": "0",
            "BENCH_LINSOLVE": "inv32", "BENCH_JAC_WINDOW": "1"},
    "bdf_nr": {"BENCH_METHOD": "bdf", "BR_EXP32": "0",
               "BENCH_LINSOLVE": "inv32nr", "BENCH_JAC_WINDOW": "1"},
    "bdf_exp32nr": {"BENCH_METHOD": "bdf", "BR_EXP32": "1",
                    "BENCH_LINSOLVE": "inv32nr", "BENCH_JAC_WINDOW": "1"},
    "bdf_exp32f": {"BENCH_METHOD": "bdf", "BR_EXP32": "1",
                   "BENCH_LINSOLVE": "inv32f", "BENCH_JAC_WINDOW": "1"},
    # the adopted accelerator default stack (PERF.md)
    "bdf_exp32f_jw8": {"BENCH_METHOD": "bdf", "BR_EXP32": "1",
                       "BENCH_LINSOLVE": "inv32f",
                       "BENCH_JAC_WINDOW": "8"},
    # window-depth probe beyond the adopted default: CVODE reuses J up to
    # ~50 steps; jw 8->16 measures whether the window is exhausted (r3
    # measured 4->8 at +7%, so expect small-but-nonzero or a tau-shift cost)
    "bdf_exp32f_jw16": {"BENCH_METHOD": "bdf", "BR_EXP32": "1",
                        "BENCH_LINSOLVE": "inv32f",
                        "BENCH_JAC_WINDOW": "16"},
}


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def child(mode, timeout, extra_env):
    env = {**os.environ, "BENCH_MODE": mode, **extra_env}
    r = run_guarded([sys.executable, BENCH], timeout, env=env)
    if r.timed_out:
        return 124, None, (r.stderr or "")[-1500:]
    parsed = None
    for ln in reversed((r.stdout or "").strip().splitlines() or [""]):
        try:
            parsed = json.loads(ln)
            break
        except (json.JSONDecodeError, ValueError):
            continue
    return r.rc, parsed, (r.stderr or "")[-1500:]


def main():
    B = os.environ.get("PERF_B", "128")
    results = {"B": int(B), "t_start": time.strftime("%Y-%m-%dT%H:%M:%S"),
               "variants": {}}

    log("pre-flight accelerator probe (90s) ...")
    rc, probe, err = child("probe", 90, {})
    if rc != 0 or probe is None or probe.get("platform") == "cpu":
        log(f"chip not healthy (rc={rc}, {probe}); aborting probe")
        sys.exit(1)
    log(f"probe ok: {probe}")

    base_tau = None
    for name, env in VARIANTS.items():
        log(f"--- variant {name} ({env or 'defaults'})")
        rc, r, err = child("rung", int(os.environ.get("PERF_TIMEOUT", "1500")),
                           {"BENCH_B": B, **env})
        if rc != 0 or r is None:
            log(f"variant {name} FAILED rc={rc}: {err[-300:]}")
            results["variants"][name] = {"rc": rc, "error": err[-300:]}
            with open(OUT, "w") as f:
                json.dump(results, f, indent=1)
            continue
        rec = {"cps": r["cps"], "wall_s": r["wall_s"], "warm_s": r["warm_s"],
               "n_ok": r["n_ok"], "mean_steps": r["mean_steps"]}
        tau = r.get("tau")
        if name == "base":
            base_tau = tau
        elif base_tau and tau:
            # None = no-ignition lane; a variant flipping a lane's ignition
            # state is a hard correctness regression, not a small drift
            mismatch = sum((a is None) != (b is None)
                           for a, b in zip(base_tau, tau))
            rels = [abs(a - b) / a for a, b in zip(base_tau, tau)
                    if a is not None and b is not None and a > 0]
            rec["tau_max_rel_diff_vs_base"] = max(rels) if rels else None
            rec["tau_ignition_mismatch_lanes"] = mismatch
            if mismatch:
                log(f"variant {name}: WARNING {mismatch} lanes flipped "
                    f"ignition state vs base — correctness regression")
        results["variants"][name] = rec
        log(f"variant {name}: {r['cps']} cond/s (wall {r['wall_s']}s, "
            f"mean steps {r['mean_steps']:.0f})"
            + (f", tau drift {rec.get('tau_max_rel_diff_vs_base', 0):.2e}"
               if name != "base" else ""))
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)

    log(f"wrote {OUT}")
    print(json.dumps(results["variants"]))


if __name__ == "__main__":
    main()
