"""Isolate the Newton-linear-algebra construction cost at bench shapes.

PERF.md's attempt-cost decomposition attributes ~2/3 of a BDF step attempt
to "RHS + analytic J + elementwise history work", with the inverse
APPLICATION measured via the inv32nr/inv32f levers — but the inverse
CONSTRUCTION (jnp.linalg.inv of the (B, S, S) f32 iteration matrix, built
fresh EVERY attempt since c = h/gamma_q changes) was never isolated: the
round-3 kernel budget timed single dispatches, which the tunneled chip's
25-77 ms roundtrip floor swamps.

This probe amortizes dispatch away: each variant is a jitted
``lax.fori_loop`` of K in-device iterations, so per-iteration numbers are
real device time.  Variants at the bench shape (B lanes, S=53 species):

  rhs        one gas RHS eval (B,S)
  jac        one analytic Jacobian build (B,S,S)
  minv_f32   build M = I - cJ and invert in f32
  minv_f64   same in f64 (double-double emulation comparison)
  matvec_f32 apply a cached f32 inverse (the inv32f per-iteration cost)
  step_ratio everything together in bench proportion: 1 jac / W attempts
             (W=8), per attempt 1 inverse + 2 matvec + 2 RHS

Writes INV_BUDGET.json.  Wedge-safe usage:
  timeout -s TERM -k 45 1500 python scripts/inv_budget.py   (background)
  IB_CPU=1 ... for the CPU control run
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("BR_EXP32", "1")

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")


def main():
    import jax

    if os.environ.get("IB_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    import batchreactor_tpu as br
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors

    B = int(os.environ.get("IB_B", "512"))
    K = int(os.environ.get("IB_K", "50"))
    log = lambda m: print(m, file=sys.stderr, flush=True)

    gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sp = list(gm.species)
    S = len(sp)
    X = np.zeros(S)
    X[sp.index("CH4")], X[sp.index("O2")], X[sp.index("N2")] = .25, .5, .25
    T = jnp.linspace(1500.0, 2000.0, B)
    y0s = sweep_solution_vectors(jnp.broadcast_to(jnp.asarray(X), (B, S)),
                                 th.molwt, T, 1e5)
    rhs = make_gas_rhs(gm, th)
    jacf = make_gas_jac(gm, th)
    vrhs = jax.vmap(lambda y, t: rhs(0.0, y, {"T": t}))
    vjac = jax.vmap(lambda y, t: jacf(0.0, y, {"T": t}))
    c = jnp.full((B,), 1e-7)
    eye = jnp.eye(S)

    def loop(body):
        # live-dependence rule: every variant folds its measured output
        # into the carry with * 1e-30 (NOT * 0.0 — a zero multiplier lets
        # the simplifier DCE the entire computation being timed) and keeps
        # the carry within 1e-30 of the physical y0s so iteration 2..K
        # evaluates on the same state as iteration 1
        def f(y0s):
            return lax.fori_loop(0, K, lambda i, y: body(y), y0s)
        return jax.jit(f)

    variants = {}

    variants["rhs"] = loop(lambda y: y + vrhs(y, T) * 1e-30)

    def jac_build(y):
        J = vjac(y, T)
        return y + J[:, :, 0] * 1e-30

    variants["jac"] = loop(jac_build)

    J0 = vjac(y0s, T)

    def minv_f32(y):
        M = eye[None] - c[:, None, None] * (J0 + y[:, :, None] * 1e-30)
        inv = jnp.linalg.inv(M.astype(jnp.float32))
        return y + inv[:, :, 0].astype(jnp.float64) * 1e-30

    variants["minv_f32"] = loop(minv_f32)

    def minv_f64(y):
        M = eye[None] - c[:, None, None] * (J0 + y[:, :, None] * 1e-30)
        inv = jnp.linalg.inv(M)
        return y + inv[:, :, 0] * 1e-30

    variants["minv_f64"] = loop(minv_f64)

    inv0 = jnp.linalg.inv(
        (eye[None] - c[:, None, None] * J0).astype(jnp.float32))

    def matvec_f32(y):
        d = jnp.einsum("bij,bj->bi", inv0, y.astype(jnp.float32))
        return y + d.astype(jnp.float64) * 1e-30

    variants["matvec_f32"] = loop(matvec_f32)

    W = 8

    def step_ratio(y):
        # bench-proportioned attempt: (1/W) jac + 1 inverse + 2 matvecs
        # + 2 RHS evals, approximated as one window of W attempts
        J = vjac(y, T)
        out = y
        for _ in range(W):
            M = eye[None] - c[:, None, None] * J
            inv = jnp.linalg.inv(M.astype(jnp.float32))
            for _ in range(2):
                r = vrhs(out, T)
                d = jnp.einsum("bij,bj->bi", inv,
                               r.astype(jnp.float32)).astype(jnp.float64)
                out = out + d * 1e-30
        return out

    def loopw(body):
        def f(y0s):
            return lax.fori_loop(0, max(1, K // W),
                                 lambda i, y: body(y), y0s)
        return jax.jit(f)

    variants["window_w8"] = loopw(step_ratio)

    results = {}
    for name, fn in variants.items():
        t0 = time.perf_counter()
        out = fn(y0s)
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = fn(y0s)
        jax.block_until_ready(out)
        wall = time.perf_counter() - t0
        iters = (max(1, K // W) * W if name == "window_w8" else K)
        per_ms = wall / iters * 1e3
        results[name] = {"total_s": round(wall, 3),
                         "per_iter_ms": round(per_ms, 3),
                         "compile_s": round(compile_s, 1)}
        log(f"{name:12s} {per_ms:8.3f} ms/iter  (compile {compile_s:.1f}s)")

    rec = {"backend": jax.default_backend(), "B": B, "S": S, "K": K,
           "variants": results}
    out_path = os.environ.get("IB_OUT", os.path.join(REPO,
                                                     "INV_BUDGET.json"))
    with open(out_path, "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
