"""Fault-injection smoke: replay all four fault classes on a tiny ODE.

The CI face of docs/robustness.md: every postmortem fault class —
hung fetch, corrupt chunk file, NaN lane, killed process — is injected
deterministically (resilience/inject.py) into a tiny stiff-decay
checkpointed sweep, recovery is asserted BIT-EXACT against an uninjected
reference on live lanes, and the collected ``fault`` events and recovery
counters are written as an obs JSONL artifact (fault_events.jsonl) — the
machine-readable record CI uploads next to the obs smoke report.

Usage:
  python scripts/fault_smoke.py [--out /tmp/fault_events.jsonl]

Exit 0 = every recovery path worked; any assertion failure exits 1 with
the traceback.  ~30 s on CPU (tiny ODE, four sweeps + two subprocesses).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the killed-process scenario needs real OS processes (os._exit does not
# unwind); the child runs the elastic tier on the same decay ODE
_ELASTIC_CHILD = r"""
import json, os, sys
pid, n, ckpt = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from batchreactor_tpu.obs.recorder import Recorder
from batchreactor_tpu.parallel import multihost as mh
from batchreactor_tpu.solver.sdirk import SUCCESS


def rhs(t, y, cfg):
    return -cfg["k"] * y


B = 8
y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
cfgs = {"k": jnp.logspace(1.0, 2.0, B)}
rec = Recorder()
res = mh.elastic_checkpointed_sweep(
    rhs, y0s, 0.0, 1.0, cfgs, ckpt, process_id=pid, num_processes=n,
    chunk_size=4, heartbeat_s=0.2, timeout_s=120.0, recorder=rec)
assert np.all(np.asarray(res.status) == SUCCESS), res.status
_s, events, counters = rec.snapshot()
print("RESULT " + json.dumps({
    "y": np.asarray(res.y).tolist(), "t": np.asarray(res.t).tolist(),
    "counters": counters,
    "fault_events": [e for e in events if e["name"] == "fault"]}))
"""


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="fault_events.jsonl",
                    help="fault-event JSONL artifact path")
    ap.add_argument("--scrape-out", default="fault_scrape.prom",
                    help="where to save the live /metrics scrape taken "
                         "WHILE the injected sweep runs (CI artifact)")
    ap.add_argument("--flight-dir", default=None,
                    help="directory for flight_*.jsonl postmortem dumps "
                         "(default: the --out directory)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from batchreactor_tpu.obs import export, report
    from batchreactor_tpu.obs.live import (LiveRegistry, MetricsServer,
                                           arm_flight, disarm_flight)
    from batchreactor_tpu.obs.recorder import Recorder
    from batchreactor_tpu.parallel.checkpoint import checkpointed_sweep
    from batchreactor_tpu.resilience import inject

    def rhs(t, y, cfg):
        return -cfg["k"] * y

    B = 8
    y0s = jnp.broadcast_to(jnp.asarray([1.0, 0.5]), (B, 2))
    cfgs = {"k": jnp.logspace(1.0, 2.0, B)}
    rec = Recorder()   # one recorder across every faulted run: the
    #                    artifact aggregates all four recovery paths
    # flight recorder armed for the whole smoke (docs/observability.md
    # "Flight recorder"): the hung-fetch wedge below dumps a
    # flight_*.jsonl postmortem, and the SIGTERM hook covers a
    # supervised teardown (run_guarded sends SIGTERM first)
    flight_dir = args.flight_dir or (os.path.dirname(
        os.path.abspath(args.out)) or ".")
    arm_flight(recorder=rec, dir=flight_dir, install_signal=True)

    def sweep(d, **kw):
        return checkpointed_sweep(rhs, y0s, 0.0, 1.0, cfgs, d,
                                  chunk_size=4, **kw)

    def assert_bit_exact(a, b, what):
        for f in ("t", "y", "status", "n_accepted", "n_rejected"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f"{what}: field {f}")
        print(f"[fault-smoke] {what}: recovered bit-exact", file=sys.stderr)

    with tempfile.TemporaryDirectory() as base:
        clean = sweep(os.path.join(base, "clean"))

        # 1 — hung fetch: watchdog breach -> WedgeError -> chunk retry,
        # with the live /metrics endpoint up and scraped WHILE the
        # injected sweep runs (the CI artifact next to the fault JSONL)
        import threading
        import urllib.request

        inject.arm("hang_fetch:delay=10")
        registry = LiveRegistry(recorder=rec, meta={"smoke": "fault"})
        scrapes = []
        stop = threading.Event()
        with MetricsServer(registry, port=0) as srv:
            url = srv.url + "/metrics"

            def scraper():
                while not stop.is_set():
                    try:
                        scrapes.append(
                            urllib.request.urlopen(url).read().decode())
                    except OSError:
                        pass
                    stop.wait(0.05)

            t = threading.Thread(target=scraper, daemon=True)
            t.start()
            try:
                res = sweep(os.path.join(base, "hang"),
                            chunk_budget_s=0.3,
                            retry={"max_retries": 2, "backoff_s": 0.0},
                            recorder=rec)
            finally:
                stop.set()
                t.join()
        assert_bit_exact(clean, res, "hung fetch")
        assert scrapes and any("br_" in s for s in scrapes), \
            "no live scrape landed while the injected sweep ran"
        # the LAST scrape carries the wedge evidence
        # (br_fault_events_total{kind="hung_fetch"})
        with open(args.scrape_out, "w") as fh:
            fh.write(scrapes[-1])
        print(f"[fault-smoke] {len(scrapes)} live scrapes during the "
              f"wedged sweep -> {args.scrape_out}", file=sys.stderr)
        import glob

        flights = glob.glob(os.path.join(flight_dir, "flight_*.jsonl"))
        assert flights, "hung-fetch wedge left no flight_*.jsonl dump"
        tail = [json.loads(ln) for ln in
                open(sorted(flights)[-1])][-8:]
        assert any(r.get("kind") == "event" and r.get("name") == "fault"
                   for r in tail), tail
        assert any(r.get("kind") == "counter_snapshot" for r in tail), tail
        print(f"[fault-smoke] flight recorder dumped "
              f"{os.path.basename(sorted(flights)[-1])} (fault event + "
              f"counter snapshot in the tail)", file=sys.stderr)

        # 2 — corrupt chunk: torn post-save, resume validates + re-solves
        inject.arm("corrupt_chunk:chunk=1")
        d = os.path.join(base, "corrupt")
        sweep(d, recorder=rec)
        res = sweep(d, recorder=rec)
        assert_bit_exact(clean, res, "corrupt chunk")

        # 3 — NaN lane: quarantine retry pass recovers it
        inject.arm("nan_lane:lane=3")
        res = sweep(os.path.join(base, "nan"), quarantine=True,
                    recorder=rec)
        assert_bit_exact(clean, res, "NaN lane")
        assert int(np.asarray(res.provenance)[3]) == 1, res.provenance

        # 4 — killed process: elastic tier reassigns the dead owner's
        # chunk to the survivor (real OS processes; p1 dies on its first
        # chunk, whose claim lands at startup — deterministic theft)
        child = os.path.join(base, "elastic_child.py")
        with open(child, "w") as fh:
            fh.write(_ELASTIC_CHILD)
        ck = os.path.join(base, "elastic")
        env = {**os.environ, "PYTHONPATH": REPO}
        procs = [subprocess.Popen(
            [sys.executable, child, str(i), "2", ck],
            env=({**env, "BR_FAULT_INJECT": "kill:chunk=1"} if i else env),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for i in range(2)]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        assert procs[1].returncode == 137, (
            f"victim survived (rc={procs[1].returncode}):\n{outs[1][-2000:]}")
        assert procs[0].returncode == 0, (
            f"survivor failed (rc={procs[0].returncode}):\n{outs[0][-2000:]}")
        got = json.loads(next(l for l in outs[0].splitlines()
                              if l.startswith("RESULT "))[len("RESULT "):])
        assert got["counters"].get("chunks_reassigned") == 1, got["counters"]
        np.testing.assert_array_equal(np.asarray(got["y"]),
                                      np.asarray(clean.y),
                                      err_msg="killed process: field y")
        print("[fault-smoke] killed process: survivor completed, bit-exact",
              file=sys.stderr)
        # fold the survivor's telemetry into the artifact recorder
        for e in got["fault_events"]:
            rec.event(e["name"], **e["attrs"])
        for k, v in got["counters"].items():
            rec.counter(k, v)

        # 5 — slow request: the serving plane joins the fault tier — a
        # deterministic stall between a request's admission into the
        # resident stream and its harvest-resolution (the slow-consumer
        # scenario).  The daemon still answers EVERY request with
        # success provenance; the stall shows up as latency on the
        # victim and as a fault event in the artifact
        from batchreactor_tpu.serving.client import SolveClient
        from batchreactor_tpu.serving.scheduler import Scheduler
        from batchreactor_tpu.serving.server import ServingServer
        from batchreactor_tpu.serving.session import SolverSession

        fixtures = os.path.join(REPO, "tests", "fixtures")
        session = SolverSession.from_spec(
            {"mechanism": {"mech": os.path.join(fixtures, "h2o2.dat"),
                           "therm": os.path.join(fixtures, "therm.dat")},
             "solver": {"segment_steps": 64, "stats": True},
             "serve": {"resident": 4, "refill": 1, "buckets": [4],
                       "poll_every": 1}}, recorder=rec)
        inject.arm("slow_request:delay=0.4,request=victim")
        comp = {"H2": 0.3, "O2": 0.15, "N2": 0.55}
        with session:
            sched = Scheduler(session)
            with ServingServer(session, sched) as srv:
                client = SolveClient(srv.url)
                rs = [client.solve({"id": rid, "T": [1150.0 + 50.0 * i],
                                    "X": comp, "t1": 5e-5})
                      for i, rid in enumerate(["pre", "victim", "post"])]
        assert all(r["provenance"] == ["success"] for r in rs), rs
        assert rs[1]["elapsed_ms"] >= 400, rs[1]["elapsed_ms"]
        print(f"[fault-smoke] slow request: victim stalled "
              f"{rs[1]['elapsed_ms']:.0f}ms between admission and "
              f"harvest, all 3 answered success", file=sys.stderr)

    disarm_flight()
    rep = report.build_report(recorder=rec,
                              meta={"smoke": "fault-injection",
                                    "faults": ["hang_fetch",
                                               "corrupt_chunk", "nan_lane",
                                               "kill", "slow_request"]})
    export.write_jsonl(args.out, rep)
    _spans, events, counters = rec.snapshot()
    kinds = sorted({e["attrs"].get("kind") for e in events
                    if e["name"] == "fault"})
    print(json.dumps({"ok": True, "out": args.out, "fault_kinds": kinds,
                      "counters": counters}))
    # the artifact must carry every injected fault kind
    missing = {"hung_fetch", "corrupt_chunk", "lane_quarantine",
               "dead_host_reassign", "slow_request"} - set(kinds)
    assert not missing, f"fault kinds missing from the artifact: {missing}"
    return 0


if __name__ == "__main__":
    sys.exit(main())
