#!/usr/bin/env python
"""Render, export, and diff batchreactor_tpu telemetry reports.

The one CLI future perf PRs cite instead of hand-run probe scripts
(PERF.md): every number it prints comes off the structured ``obs`` report
(docs/observability.md) — host spans, device-side solver counters, and
compile/retrace counts.

  # run a file-driven case with telemetry and render the report
  python scripts/obs_report.py --run tests/fixtures/batch_h2o2.xml \\
      --lib tests/fixtures --gaschem --out /tmp/h2o2.jsonl

  # render a stored report
  python scripts/obs_report.py /tmp/h2o2.jsonl

  # machine-readable re-exports
  python scripts/obs_report.py /tmp/h2o2.jsonl --json     # JSONL to stdout
  python scripts/obs_report.py /tmp/h2o2.jsonl --prom     # Prometheus text

  # before/after comparison (the perf-PR workflow)
  python scripts/obs_report.py --diff baseline.jsonl candidate.jsonl
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render / export / diff obs telemetry reports")
    ap.add_argument("report", nargs="?", help="stored report (.jsonl)")
    ap.add_argument("--run", metavar="BATCH_XML",
                    help="run a file-driven case with telemetry=True and "
                         "report on it")
    ap.add_argument("--lib", default=os.path.join(REPO, "tests", "fixtures"),
                    help="mechanism library dir for --run (default: the "
                         "vendored test fixtures)")
    ap.add_argument("--gaschem", action="store_true",
                    help="--run with gas chemistry")
    ap.add_argument("--surfchem", action="store_true",
                    help="--run with surface chemistry")
    ap.add_argument("--out", help="also write the report as JSONL here "
                                  "(the CI artifact)")
    ap.add_argument("--json", action="store_true",
                    help="print the JSONL export instead of the rendering")
    ap.add_argument("--prom", action="store_true",
                    help="print the Prometheus text exposition instead")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="diff two stored reports (baseline -> candidate)")
    ap.add_argument("--timeline", action="store_true",
                    help="render the per-lane solver timelines instead "
                         "(needs a report from a timeline=N run; "
                         "docs/observability.md 'Solver timelines')")
    ap.add_argument("--lanes",
                    help="comma-separated lane indices for --timeline "
                         "(default: the most-rejecting lanes)")
    args = ap.parse_args(argv)

    from batchreactor_tpu import obs

    if args.diff:
        a, b = (obs.read_jsonl(p) for p in args.diff)
        print(obs.diff(a, b))
        return 0

    if args.run:
        import shutil
        import tempfile

        import batchreactor_tpu as br

        if not (args.gaschem or args.surfchem):
            args.gaschem = True  # the common fixture case
        # profile files land next to the input XML; run from a scratch
        # copy so --run never writes into the repo or a read-only tree
        with tempfile.TemporaryDirectory() as tmp:
            xml = os.path.join(tmp, os.path.basename(args.run))
            shutil.copy(args.run, xml)
            ret, report = br.batch_reactor(
                xml, args.lib, gaschem=args.gaschem,
                surfchem=args.surfchem, verbose=False, telemetry=True)
        print(f"status: {ret}", file=sys.stderr)
    elif args.report:
        report = obs.read_jsonl(args.report)
    else:
        ap.error("give a stored report, --run, or --diff")

    if args.out:
        obs.write_jsonl(args.out, report)
        print(f"wrote {args.out}", file=sys.stderr)
    if args.json:
        sys.stdout.write(obs.to_jsonl(report))
    elif args.prom:
        sys.stdout.write(obs.to_prometheus(report))
    elif args.timeline:
        lanes = ([int(x) for x in args.lanes.split(",")]
                 if args.lanes else None)
        print(obs.timeline.render(report, lanes=lanes))
    else:
        print(obs.render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
