"""Per-attempt kernel budget: time the BDF step's components on the device.

PERF.md's trace question — where does the ms per batched step attempt go
under f64 emulation? — answered by timing each component as its own jitted
program at the bench shape (GRI-3.0, B lanes):

  rhs        one RHS evaluation (B, 53) -> (B, 53)
  jac        analytic Jacobian (B, 53, 53)
  inv32      f32 batched inverse of the iteration matrix
  matvec64   (B, 53, 53) @ (B, 53) in emulated f64  (inv32nr's solve)
  matvec32   same in native f32                     (inv32f's solve)
  attempt    one full vmapped BDF step attempt (J + inverse + Newton + err)

Each timing is min-of-5 after a warm-up call (steady-state dispatch, the
regime the segmented sweep runs in).  Component sum vs the measured
attempt time shows how much XLA fusion claws back.  Writes
KERNEL_BUDGET.json and prints it.

Usage: python scripts/kernel_budget.py        # B=384 on the default device
       KB_B=128 python scripts/kernel_budget.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("BR_EXP32", "1")  # the bench configuration

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")


def timed(fn, *args, n=5):
    """Min-of-n steady-state wall time of a jitted callable (seconds)."""
    import jax

    jax.block_until_ready(fn(*args))  # warm-up / compile
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.solver import bdf

    B = int(os.environ.get("KB_B", "384"))
    gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sp = list(gm.species)
    S = len(sp)
    x0 = np.zeros(S)
    x0[sp.index("CH4")], x0[sp.index("O2")], x0[sp.index("N2")] = .25, .5, .25
    T = jnp.linspace(1500.0, 2000.0, B)
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors

    ys = sweep_solution_vectors(
        jnp.broadcast_to(jnp.asarray(x0), (B, S)), th.molwt, T, 1e5)
    rhs = make_gas_rhs(gm, th)
    jacf = make_gas_jac(gm, th)

    rhs_b = jax.jit(jax.vmap(lambda y, t: rhs(0.0, y, {"T": t})))
    jac_b = jax.jit(jax.vmap(lambda y, t: jacf(0.0, y, {"T": t})))
    J = jac_b(ys, T)
    c = jnp.asarray(1e-7)
    M = jnp.eye(S)[None] - c * J
    inv_b = jax.jit(lambda m: jnp.linalg.inv(m.astype(jnp.float32)))
    Minv32 = inv_b(M)
    Minv64 = Minv32.astype(jnp.float64)
    mv64 = jax.jit(lambda a, b: jnp.einsum("bij,bj->bi", a, b))
    mv32 = jax.jit(lambda a, b: jnp.einsum(
        "bij,bj->bi", a, b.astype(jnp.float32)).astype(jnp.float64))

    def one_attempt(y, t):
        # the body of one BDF step attempt at order 1, matching the real
        # per-attempt kernel chain (J + M + inv + Newton loop + error norm).
        # dt0 pins a representative step size (the cold-start Hairer
        # heuristic would make Newton trivially easy); the solve prologue
        # (f0 eval, init norms, result assembly) is still included, so read
        # this as an upper bound on one steady-state attempt
        res = bdf.solve(rhs, y, 0.0, 1e-7, {"T": t}, rtol=1e-6, atol=1e-10,
                        jac=jacf, max_steps=1, n_save=0, dt0=1e-7)
        return res.y

    att_b = jax.jit(jax.vmap(one_attempt))

    out = {
        "B": B, "device": jax.default_backend(),
        "exp32": os.environ.get("BR_EXP32") == "1",
        "ms": {
            "rhs": timed(rhs_b, ys, T) * 1e3,
            "jac": timed(jac_b, ys, T) * 1e3,
            "inv32": timed(inv_b, M) * 1e3,
            "matvec64": timed(mv64, Minv64, ys) * 1e3,
            "matvec32": timed(mv32, Minv32, ys) * 1e3,
            "attempt": timed(att_b, ys, T) * 1e3,
        },
    }
    out["ms"] = {k: round(v, 3) for k, v in out["ms"].items()}
    with open(os.path.join(REPO, "KERNEL_BUDGET.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
