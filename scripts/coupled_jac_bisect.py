"""Bisect the coupled-Jacobian TPU compile wall INSIDE the jac program.

Round-4's clean localization ladder (scripts/coupled_compile_probe.py, run
on a fresh healthy chip) pinned the wall to stage s2: ``jit(vmap(
make_surface_jac(sm, th, gm=gm)))`` at B=64 times out at 600 s while the
single-lane surface kernel (s1) compiles in ~6 s and the gas-only analytic
Jacobian compiles inside the full BDF bench program in ~180 s.  This script
splits s2 along its three axes — vmap batching, gas-block coupling, and the
final ``jnp.block`` assembly — one subprocess per variant (SIGTERM-first
timeouts; a SIGKILLed TPU client wedges the tunnel, PERF.md):

  j0_surf_only   vmap B, surface blocks only (gm=None)
  j1_gas_only    vmap B, gas analytic jac alone (make_gas_jac)
  j2_no_block    vmap B, coupled, returns the 4 blocks WITHOUT jnp.block
  j3_full        vmap B, coupled, jnp.block — the s2 reproduction
  j4_single      coupled + block, single lane (no vmap)
  j5_small_b     coupled + block, vmap B=8 — compile-time scaling in B
  j6_barrier     j3 with BR_JAC_BARRIER=1 (optimization_barrier fences the
                 four blocks before assembly) — fix candidate
  j7_low_effort  j3 compiled with exec_time_optimization_effort=-1.0 —
                 fix candidate (skips expensive late optimization passes)

RHS-axis stages (added after the localization ladder found s3 — the
coupled RHS with NO Jacobian — also walls, so the trigger predates the
Jacobian assembly):

  r0_surf_rhs    vmap B, surface-only RHS (gm=None)
  r1_coupled_rhs vmap B, coupled RHS — the s3 reproduction
  r2_rhs_single  coupled RHS, single lane (no vmap)
  r3_surf_kernel vmap B, bare surface production_rates kernel
  r4_rhs_low     r1 at exec_time_optimization_effort=-1.0 — fix candidate
  r5_roundtrip   vmap B, just the mass->mole->pressure round-trip the
                 surface path does and the gas-only path reduces away

Writes JAC_BISECT.json incrementally.  Usage (background task):
  python scripts/coupled_jac_bisect.py
  CJB_STAGES=j2_no_block,j4_single CJB_TIMEOUT=900 CJB_B=64 ...
"""

import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# THE SIGTERM-with-grace rule lives in resilience/guard.py (stdlib-only);
# loaded from its file so the parent ladder never imports jax
_spec = importlib.util.spec_from_file_location(
    "_br_resilience_guard",
    os.path.join(REPO, "batchreactor_tpu", "resilience", "guard.py"))
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)
run_guarded = _guard.run_guarded

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")

STAGES = ["r5_roundtrip", "r3_surf_kernel", "r0_surf_rhs", "r2_rhs_single",
          "r1_coupled_rhs", "r4_rhs_low",
          "j0_surf_only", "j1_gas_only", "j2_no_block", "j3_full",
          "j4_single", "j5_small_b", "j6_barrier", "j7_low_effort"]


def _stage_main(stage):
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    os.environ.setdefault("BR_EXP32", "1")
    import jax

    if os.environ.get("CJB_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.models.surface import compile_mech
    from batchreactor_tpu.ops import surface_kinetics
    from batchreactor_tpu.ops.rhs import (make_gas_jac, make_surface_jac,
                                          make_surface_rhs)
    from batchreactor_tpu.parallel.grid import sweep_solution_vectors

    B = int(os.environ.get("CJB_B", "64"))
    gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sm = compile_mech(f"{LIB}/ch4ni.xml", th, list(gm.species))
    sp = list(gm.species)
    ng = len(sp)

    X = np.zeros(ng)
    X[sp.index("CH4")], X[sp.index("O2")], X[sp.index("N2")] = .25, .5, .25
    T_grid = jnp.linspace(1073.0, 1273.0, B)
    y0s = sweep_solution_vectors(jnp.broadcast_to(jnp.asarray(X), (B, ng)),
                                 th.molwt, T_grid, 1e5,
                                 ini_covg=sm.ini_covg)
    cfg = {"T": T_grid, "Asv": jnp.full((B,), 1.0)}
    in_axes = (None, 0, {"T": 0, "Asv": 0})

    t0 = time.perf_counter()
    if stage in ("r0_surf_rhs", "r1_coupled_rhs", "r2_rhs_single",
                 "r4_rhs_low"):
        rhsf = make_surface_rhs(sm, th,
                                gm=None if stage == "r0_surf_rhs" else gm)
        if stage == "r2_rhs_single":
            f = jax.jit(rhsf)
            out = f(0.0, y0s[0], {"T": T_grid[0], "Asv": jnp.asarray(1.0)})
        elif stage == "r4_rhs_low":
            f = jax.jit(jax.vmap(rhsf, in_axes=in_axes))
            compiled = f.lower(0.0, y0s, cfg).compile(compiler_options={
                "exec_time_optimization_effort": -1.0})
            out = compiled(0.0, y0s, cfg)
        else:
            f = jax.jit(jax.vmap(rhsf, in_axes=in_axes))
            out = f(0.0, y0s, cfg)
    elif stage == "r5_roundtrip":
        from batchreactor_tpu.utils.composition import (mass_to_mole,
                                                        pressure)

        def roundtrip(y, T):
            rho_k = y[:ng]
            rho = jnp.sum(rho_k)
            x = mass_to_mole(rho_k / rho, th.molwt)
            return x * pressure(rho, x, th.molwt, T)

        f = jax.jit(jax.vmap(roundtrip, in_axes=(0, 0)))
        out = f(y0s, T_grid)
    elif stage == "r3_surf_kernel":
        gamma_sig = None

        def kernel(T, x, theta):
            return surface_kinetics.production_rates(T, 1e5, x, theta, sm)

        X_b = jnp.broadcast_to(jnp.asarray(X), (B, ng))
        f = jax.jit(jax.vmap(kernel, in_axes=(0, 0, None)))
        out = f(T_grid, X_b, sm.ini_covg)
    elif stage == "j0_surf_only":
        jacf = make_surface_jac(sm, th, gm=None)
        # gm=None sizes the gas block by thermo.species; the surface-state
        # vector is unchanged (same y layout), so y0s works as-is
        f = jax.jit(jax.vmap(jacf, in_axes=in_axes))
        out = f(0.0, y0s, cfg)
    elif stage == "j1_gas_only":
        jacg = make_gas_jac(gm, th)
        f = jax.jit(jax.vmap(lambda t, y, c: jacg(t, y, {"T": c["T"]}),
                             in_axes=in_axes))
        out = f(0.0, y0s[:, :ng], cfg)
    elif stage in ("j2_no_block", "j3_full", "j4_single", "j5_small_b",
                   "j6_barrier", "j7_low_effort"):
        # j2: the four blocks straight from the kernel — the traced program
        # truly lacks the jnp.block concat (slicing it back out would leave
        # the concat in the program; ADVICE r4).  j6: explicit
        # fence_blocks=True — BR_JAC_BARRIER is frozen at module import now
        # (ADVICE r5), an in-process env poke after import is ignored
        jacf = make_surface_jac(sm, th, gm=gm,
                                return_blocks=stage == "j2_no_block",
                                fence_blocks=(True if stage == "j6_barrier"
                                              else None))
        if stage == "j4_single":
            f = jax.jit(jacf)
            out = f(0.0, y0s[0],
                    {"T": T_grid[0], "Asv": jnp.asarray(1.0)})
        elif stage == "j7_low_effort":
            f = jax.jit(jax.vmap(jacf, in_axes=in_axes))
            lowered = f.lower(0.0, y0s, cfg)
            compiled = lowered.compile(compiler_options={
                "exec_time_optimization_effort": -1.0})
            out = compiled(0.0, y0s, cfg)
        else:
            if stage == "j5_small_b":
                y0s, cfg = y0s[:8], {k: v[:8] for k, v in cfg.items()}
            f = jax.jit(jax.vmap(jacf, in_axes=in_axes))
            out = f(0.0, y0s, cfg)
    else:
        raise SystemExit(f"unknown stage {stage}")
    jax.block_until_ready(out)
    print(json.dumps({"stage": stage, "ok": True,
                      "backend": jax.default_backend(), "B": B,
                      "compile_and_run_s": round(time.perf_counter() - t0,
                                                 1)}))


def main():
    if os.environ.get("CJB_STAGE"):
        _stage_main(os.environ["CJB_STAGE"])
        return

    timeout = int(os.environ.get("CJB_TIMEOUT", "600"))
    stages = (os.environ.get("CJB_STAGES", "").split(",")
              if os.environ.get("CJB_STAGES") else STAGES)
    out_path = os.environ.get("CJB_OUT", os.path.join(REPO,
                                                      "JAC_BISECT.json"))
    results = []
    for stage in stages:
        print(f"--- {stage} (timeout {timeout}s)", file=sys.stderr,
              flush=True)
        env = {**os.environ, "CJB_STAGE": stage}
        r = run_guarded([sys.executable, os.path.abspath(__file__)],
                        timeout, env=env)
        rec = {"stage": stage, "rc": r.rc, "timed_out": r.timed_out,
               "wall_s": round(r.wall_s, 1)}
        for line in (r.stdout or "").splitlines():
            try:
                rec.update(json.loads(line))
                break
            except json.JSONDecodeError:
                continue
        if not rec.get("ok"):
            rec["stderr_tail"] = (r.stderr or "")[-800:]
        results.append(rec)
        print(json.dumps(rec), file=sys.stderr, flush=True)
        with open(out_path, "w") as fh:
            json.dump({"stages": results, "B": os.environ.get("CJB_B", "64"),
                       "lib": LIB}, fh, indent=1)
    print(json.dumps({"stages": results}))


if __name__ == "__main__":
    main()
