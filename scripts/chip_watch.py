"""Wait for the tunneled TPU to come back, then exit 0.

The chip wedges for hours (PERF.md); this watcher lets an operator start
on-chip work the moment it returns instead of polling by hand.  Every
``CW_INTERVAL`` seconds it runs a tiny device probe in a subprocess with a
SIGTERM-first timeout (a SIGKILLed axon client can deepen a tunnel wedge —
round-2/3 postmortems), appending one status line per attempt to stderr.
Exits 0 the first time the probe succeeds; exits 1 when ``CW_MAX_S`` is
exhausted without a healthy probe.

Usage (background task):  python scripts/chip_watch.py
  CW_INTERVAL=600 CW_MAX_S=39600 CW_PROBE_TIMEOUT=120 ...
"""

import importlib.util
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# THE SIGTERM-with-grace rule lives in resilience/guard.py (stdlib-only);
# loaded from its file so this watcher never imports jax itself
_spec = importlib.util.spec_from_file_location(
    "_br_resilience_guard",
    os.path.join(REPO, "batchreactor_tpu", "resilience", "guard.py"))
_guard = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_guard)
run_guarded = _guard.run_guarded

PROBE = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256)) @ jnp.ones((256, 256));"
    "jax.block_until_ready(x);"
    "print('healthy', jax.default_backend(), len(jax.devices()))"
)


def probe_once(timeout):
    r = run_guarded([sys.executable, "-c", PROBE], timeout)
    if r.timed_out:
        return False, "timeout"
    return r.rc == 0 and "healthy" in (r.stdout or ""), r.stdout


def main():
    interval = int(os.environ.get("CW_INTERVAL", "600"))
    max_s = int(os.environ.get("CW_MAX_S", "39600"))
    probe_timeout = int(os.environ.get("CW_PROBE_TIMEOUT", "120"))
    t0 = time.time()
    attempt = 0
    while time.time() - t0 < max_s:
        attempt += 1
        ok, out = probe_once(probe_timeout)
        stamp = time.strftime("%H:%M:%S")
        print(f"[{stamp}] attempt {attempt}: "
              f"{'HEALTHY' if ok else 'wedged'} ({(out or '').strip()})",
              file=sys.stderr, flush=True)
        if ok:
            print("chip healthy")
            return 0
        time.sleep(interval)
    print("gave up: chip never returned", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
