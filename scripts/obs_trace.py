#!/usr/bin/env python
"""Render per-request latency waterfalls from trace JSONL.

Input is an obs report JSONL (``scripts/serve.py --obs-out`` /
``serve_bench.py --obs-out``, or any ``obs.write_jsonl`` artifact):
every ``request_trace`` event — one per request the serving scheduler
resolved (obs/trace.py) — renders as a stage waterfall, so "where did
this request's latency go" is one command against the daemon's run
record:

  # every request, arrival order
  python scripts/obs_trace.py /tmp/serve_obs.jsonl

  # the 10 slowest (the latency-triage view)
  python scripts/obs_trace.py /tmp/serve_obs.jsonl --slowest 10

  # only requests past 250 ms, machine-readable
  python scripts/obs_trace.py /tmp/serve_obs.jsonl --threshold-ms 250 --json

  # FLEET mode: stitch router + member streams into cross-host
  # waterfalls (docs/observability.md "Fleet tracing")
  python scripts/obs_trace.py --fleet /tmp/fleet/obs --slowest 10

Stages (docs/observability.md "Request tracing"):
``submitted -> coalesced`` queue wait + coalesce window,
``-> admitted`` epoch hand-off, ``-> first_harvest`` resident solve,
``-> stalled`` (injected fault only), ``-> resolved`` harvest tail.

``--fleet DIR`` reads the ``serve_fleet.py --obs-dir`` layout
(``router.jsonl`` + one ``<member>.jsonl`` per member), joins each
router hop ledger with its member's stage waterfall
(``obs.stitch`` — clock-skew corrected by the router's send/recv
bracket), and renders per-hop + per-stage attribution with failover
chains flagged; ``--json`` emits the stitched trace records.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: waterfall bar budget (columns for the longest segment on display)
_BAR = 36


def load_traces(report):
    """The ``request_trace`` event attribute dicts of a report, in
    event (= resolution) order."""
    out = []
    for e in report.get("events") or []:
        if e.get("name") == "request_trace":
            out.append(dict(e.get("attrs") or {}))
    return out


def select_traces(traces, slowest=None, threshold_ms=None):
    """THE filter both output modes share: drop requests under the
    threshold, then (``slowest``) keep the N largest totals, slowest
    first; otherwise resolution order is preserved."""
    if threshold_ms is not None:
        traces = [t for t in traces
                  if 1e3 * float(t.get("total_s", 0.0)) >= threshold_ms]
    if slowest is not None:
        traces = sorted(traces, key=lambda t: -float(t.get("total_s",
                                                           0.0)))
        traces = traces[:int(slowest)]
    return traces


def render_waterfalls(traces, slowest=None, threshold_ms=None):
    """The multi-line waterfall rendering (module doc) over trace
    attribute dicts (``RequestTrace.to_attrs`` shape)."""
    from batchreactor_tpu.obs.trace import STAGE_ORDER

    traces = select_traces(traces, slowest=slowest,
                           threshold_ms=threshold_ms)
    order = ("slowest first" if slowest is not None
             else "resolution order")
    if not traces:
        return "(no request_trace events match)"
    lines = [f"request waterfalls ({len(traces)} requests, {order})"]
    scale = max(max((d for t in traces
                     for d in (t.get("segments") or {}).values()),
                    default=0.0), 1e-9)
    for t in traces:
        total_ms = 1e3 * float(t.get("total_s", 0.0))
        head = (f"{t.get('request', '?')}  lanes={t.get('lanes', '?')}  "
                f"total {total_ms:.1f}ms")
        if t.get("failed"):
            head += "  [FAILED]"
        lines.append(head)
        segs = t.get("segments") or {}
        stages = t.get("stages") or {}
        prev = "submitted"
        for stage in STAGE_ORDER[1:]:
            if stage not in segs and stage not in stages:
                continue
            dur = float(segs.get(stage, 0.0))
            bar = "#" * max(1 if dur > 0 else 0,
                            round(_BAR * dur / scale))
            lines.append(f"  {prev + ' -> ' + stage:<28s} "
                         f"{1e3 * dur:9.2f}ms  {bar}")
            prev = stage
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("report", nargs="?",
                    help="obs report JSONL with request_trace events "
                         "(single-host mode)")
    ap.add_argument("--fleet", metavar="DIR",
                    help="fleet obs dir (serve_fleet.py --obs-dir "
                         "layout): stitch router + member streams "
                         "into cross-host waterfalls")
    ap.add_argument("--slowest", type=int, metavar="N",
                    help="render only the N slowest requests, "
                         "slowest first")
    ap.add_argument("--threshold-ms", type=float,
                    help="drop requests faster than this")
    ap.add_argument("--json", action="store_true",
                    help="emit the matching trace records as JSONL "
                         "instead of the rendering")
    ap.add_argument("--out", help="also write the rendering here")
    args = ap.parse_args(argv)
    if (args.report is None) == (args.fleet is None):
        ap.error("exactly one of REPORT or --fleet DIR is required")

    from batchreactor_tpu import obs

    if args.fleet:
        from batchreactor_tpu.obs import stitch as fleet_stitch

        stitched = fleet_stitch.stitch(fleet_stitch.load_fleet(
            args.fleet))
        if args.json:
            for t in fleet_stitch.select_traces(
                    stitched, slowest=(args.slowest
                                       if args.slowest is not None
                                       else len(stitched)),
                    threshold_ms=args.threshold_ms):
                print(json.dumps(t, sort_keys=True))
            return 0
        text = fleet_stitch.render_fleet(
            stitched, slowest=(args.slowest
                               if args.slowest is not None
                               else len(stitched)),
            threshold_ms=args.threshold_ms)
    else:
        traces = load_traces(obs.read_jsonl(args.report))
        if args.json:
            for t in select_traces(traces, slowest=args.slowest,
                                   threshold_ms=args.threshold_ms):
                print(json.dumps(t, sort_keys=True))
            return 0
        text = render_waterfalls(traces, slowest=args.slowest,
                                 threshold_ms=args.threshold_ms)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
