#!/usr/bin/env python
"""Evaluate SLO objectives over stitched fleet traces; gate in CI.

Input is a fleet obs dir (the ``scripts/serve_fleet.py --obs-dir``
layout: ``router.jsonl`` + one ``<member>.jsonl`` per member) or any
single obs report JSONL with ``request_trace`` events.  The traces are
stitched (``obs.stitch``) and the default objectives (``obs.slo``:
p95 end-to-end latency, error rate, failover rate) — or the baseline's
own — evaluate over them:

  # the summary table
  python scripts/obs_slo.py --fleet /tmp/fleet/obs

  # CI gate: exit nonzero when any objective breaches its band
  python scripts/obs_slo.py --fleet /tmp/fleet/obs --gate \\
      --baseline tests/fixtures/fleet_slo_baseline.json

Baseline grammar (schema ``br-slo-gate-v1``)::

    {"schema": "br-slo-gate-v1",
     "objectives": {
       "latency_p95":   {"kind": "latency", "budget": 0.05,
                         "threshold_s": 2.5,
                         "bad_fraction": {"max": 0.05}},
       "error_rate":    {"kind": "error", "budget": 0.01,
                         "bad_fraction": {"max": 0.0}},
       "failover_rate": {"kind": "failover", "budget": 0.05,
                         "bad_fraction": {"max": 0.5}}},
     "requests": {"min": 1}}

Each objective entry declares the contract (``kind`` / ``budget`` /
``threshold_s`` — the ``obs.slo.Objective`` fields) plus tolerance
bands (``{"min","max","equals"}`` — the ``obs_gate.py`` band grammar)
over the measured ``bad_fraction`` / ``bad`` / ``requests`` / ``burn``;
an omitted band means "just the budget check" (``bad_fraction <=
budget``).  ``requests`` at the top level bands the stitched-trace
count, so an empty run fails loudly instead of vacuously passing.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from obs_gate import _check_band, _fmt  # noqa: E402 (sibling script)

#: the banked-baseline schema this gate speaks — bump on any grammar
#: change
SLO_GATE_SCHEMA = "br-slo-gate-v1"

#: per-objective result fields a baseline may band
_BANDABLE = ("requests", "bad", "bad_fraction", "burn")


def load_objectives(baseline):
    """The baseline's objectives as ``obs.slo.Objective`` instances
    (``None`` -> the library defaults)."""
    from batchreactor_tpu.obs.slo import Objective

    if baseline is None or "objectives" not in baseline:
        return None
    objs = []
    for name, spec in sorted(baseline["objectives"].items()):
        objs.append(Objective(name, spec["kind"], spec["budget"],
                              threshold_s=spec.get("threshold_s")))
    return tuple(objs)


def run_slo_gate(baseline, results, n_traces):
    """Band every objective's measurements; ``(failures, lines)`` —
    the ``obs_gate.run_gate`` contract."""
    if baseline.get("schema", SLO_GATE_SCHEMA) != SLO_GATE_SCHEMA:
        raise ValueError(f"unsupported SLO gate schema "
                         f"{baseline.get('schema')!r} (this gate "
                         f"speaks {SLO_GATE_SCHEMA})")
    known = {"schema", "description", "objectives", "requests"}
    unknown = sorted(set(baseline) - known)
    if unknown:
        raise ValueError(f"unknown SLO gate section(s) {unknown}; "
                         f"known: {sorted(known)}")
    lines, failures = [], []

    def row(ok, name, value, detail):
        line = (f"  [{'ok' if ok else 'FAIL':>4s}] {name}: "
                f"{_fmt(value)} (want {detail})")
        lines.append(line)
        if not ok:
            failures.append(line)

    if "requests" in baseline:
        ok, detail = _check_band(n_traces, baseline["requests"])
        row(ok, "stitched traces", n_traces, detail)
    for name, spec in sorted((baseline.get("objectives") or {}).items()):
        res = results[name]
        row(res["ok"], f"{name} budget", res["bad_fraction"],
            f"<= {res['budget']} (budget)")
        for field in _BANDABLE:
            if field in spec:
                ok, detail = _check_band(res[field], spec[field])
                row(ok, f"{name} {field}", res[field], detail)
    return failures, lines


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("report", nargs="?",
                    help="single obs report JSONL (request_trace "
                         "events)")
    ap.add_argument("--fleet", metavar="DIR",
                    help="fleet obs dir (serve_fleet.py --obs-dir "
                         "layout) to stitch and evaluate")
    ap.add_argument("--baseline",
                    help="banked br-slo-gate-v1 JSON (objectives + "
                         "tolerance bands)")
    ap.add_argument("--gate", action="store_true",
                    help="exit nonzero when any objective breaches "
                         "(CI mode; requires --baseline)")
    ap.add_argument("--json", action="store_true",
                    help="emit the evaluation as JSON instead of the "
                         "table")
    args = ap.parse_args(argv)
    if (args.report is None) == (args.fleet is None):
        ap.error("exactly one of REPORT or --fleet DIR is required")
    if args.gate and not args.baseline:
        ap.error("--gate requires --baseline")

    from batchreactor_tpu.obs import read_jsonl
    from batchreactor_tpu.obs.slo import evaluate_traces
    from batchreactor_tpu.obs.stitch import load_fleet, stitch

    if args.fleet:
        reports = load_fleet(args.fleet)
    else:
        reports = [(os.path.splitext(os.path.basename(
            args.report))[0], read_jsonl(args.report))]
    traces = stitch(reports)
    baseline = None
    if args.baseline:
        with open(args.baseline) as f:
            baseline = json.load(f)
    results = evaluate_traces(traces, load_objectives(baseline))
    if args.json:
        print(json.dumps({"schema": SLO_GATE_SCHEMA,
                          "traces": len(traces),
                          "objectives": results}, sort_keys=True))
        if args.gate:
            failures, _ = run_slo_gate(baseline, results, len(traces))
            return 1 if failures else 0
        return 0
    print(f"SLO evaluation over {len(traces)} stitched trace(s) "
          f"({'fleet ' + args.fleet if args.fleet else args.report}):")
    for name, res in sorted(results.items()):
        thr = (f" threshold={res['threshold_s']}s"
               if "threshold_s" in res else "")
        print(f"  {name} [{res['kind']}]{thr}: "
              f"{res['bad']}/{res['requests']} bad "
              f"(fraction {res['bad_fraction']}, budget "
              f"{res['budget']}, burn {res['burn']}) "
              f"{'ok' if res['ok'] else 'BREACH'}")
    if baseline is not None:
        failures, lines = run_slo_gate(baseline, results, len(traces))
        print("gate:")
        print("\n".join(lines))
        if failures:
            print(f"SLO GATE FAILED ({len(failures)} breach(es))")
            return 1 if args.gate else 0
        print("slo gate ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
