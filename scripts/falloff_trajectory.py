"""Integrate the full 10 s batch_gas_and_surf config under candidate falloff
conventions and score each against all 1919 golden rows."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np
from scipy.integrate import solve_ivp
import batchreactor_tpu as br
from batchreactor_tpu.models.surface import compile_mech
from batchreactor_tpu.ops import gas_kinetics as gk, surface_kinetics
from batchreactor_tpu.ops.thermo import gibbs_over_RT
from batchreactor_tpu.utils.constants import R

LIB = "/root/reference/test/lib"
GOLD = "/root/reference/test/batch_gas_and_surf"
gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
sp = list(gm.species)
sm = compile_mech(f"{LIB}/ch4ni.xml", th, sp)
molwt = np.asarray(th.molwt)
T = 1173.0

gold = np.loadtxt(f"{GOLD}/gas_profile.csv", delimiter=",", skiprows=1)
gcov = np.loadtxt(f"{GOLD}/surface_covg.csv", delimiter=",", skiprows=1)

def make_rhs(falloff_mode):
    """gas+surf RHS with parameterized falloff: 'phys' | 'cmc' (xL xF xcMcgs)."""
    def gas_wdot(conc):
        kinf = gk._arrhenius(T, gm.log_A, gm.beta, gm.Ea)
        k0 = gk._arrhenius(T, gm.log_A0, gm.beta0, gm.Ea0)
        cM = gm.eff @ conc
        Pr = k0 / jnp.maximum(kinf, 1e-300) * jnp.maximum(cM, 0.0)
        L = Pr / (1 + Pr)
        F = gk._troe_F(jnp.asarray(T), Pr, gm.troe, gm.has_troe)
        kf_fall = kinf * L * F
        if falloff_mode == "cmc":
            kf_fall = kf_fall * jnp.maximum(cM, 0.0) * 1e-6
        kf = jnp.where(gm.has_falloff > 0, kf_fall, kinf)
        tb = jnp.where(gm.has_tb > 0, cM, 1.0)
        g = gibbs_over_RT(T, th)
        dnu = gm.nu_r - gm.nu_f
        dG = dnu @ g
        dn = dnu.sum(axis=1)
        lKc = -dG + dn * (jnp.log(1e5 / (R * T)) + jnp.log(1e6))  # quirk
        kr = jnp.where(gm.rev_mask > 0, kf * jnp.exp(jnp.clip(-lKc, -690, 690)), 0.0)
        safe = jnp.maximum(conc, 0.0)
        lg = jnp.log(jnp.maximum(safe, 1e-300))
        qf = jnp.exp(gm.nu_f @ lg)
        qr = jnp.exp(gm.nu_r @ lg)
        q = tb * (kf * qf - kr * qr)
        return dnu.T @ q

    ng = len(sp)
    def rhs(t, y):
        y = jnp.asarray(y)
        rho_k, theta = y[:ng], y[ng:]
        rho = jnp.sum(rho_k)
        Y = rho_k / rho
        wbar = 1.0 / jnp.sum(Y / th.molwt)
        x = Y * wbar / th.molwt
        p = rho * R * T / wbar
        sg, ss = surface_kinetics.production_rates(T, p, x, theta, sm)
        conc = rho_k / th.molwt
        w = gas_wdot(conc)
        dy = (sg + w) * th.molwt
        dth = ss * sm.site_coordination / (sm.site_density * 1e4)
        return jnp.concatenate([dy, dth])
    return jax.jit(rhs)

x0 = gold[0, 4:]
rho0 = gold[0, 3]
wbar0 = (x0 * molwt).sum()
y0 = np.concatenate([rho0 * x0 * molwt / wbar0, np.asarray(sm.ini_covg)])

sample = np.unique(np.concatenate([
    np.searchsorted(gold[:, 0], [1e-6, 1e-4, 1e-3, 1e-2, 0.1, 0.5, 1, 2, 4, 6, 8]),
    [len(gold) - 1]]))
t_eval = gold[sample, 0]

for mode in ["phys", "cmc"]:
    f = make_rhs(mode)
    fn = lambda t, y: np.asarray(f(t, y))
    t0 = time.time()
    sol = solve_ivp(fn, (0, 10.0), y0, method="BDF", rtol=1e-8, atol=1e-12,
                    t_eval=t_eval)
    print(f"\n=== falloff={mode}: {time.time()-t0:.0f}s, ok={sol.success}")
    for j, it in enumerate(sample):
        yk = sol.y[:53, j]
        x = (yk / molwt) / (yk / molwt).sum()
        gx = gold[it, 4:]
        key = [("CH4", None), ("H2O", None), ("CO2", None), ("CO", None),
               ("H2", None), ("C2H6", None)]
        line = f"t={gold[it,0]:.3g}: "
        for name, _ in key:
            i = sp.index(name)
            if abs(gx[i]) > 1e-12:
                line += f"{name} {x[i]/gx[i]:.3f} "
            else:
                line += f"{name} ours={x[i]:.1e}|g={gx[i]:.1e} "
        print(line)
