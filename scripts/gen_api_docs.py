"""Generate docs/api.md from the package's live docstrings.

Role-equivalent of Documenter.jl's `@autodocs` blocks
(/root/reference/docs/make.jl:1-26): the API reference is extracted from
the installed package, so it cannot drift from the code — CI regenerates
it and fails if the committed page is stale (`--check`).

Usage:
  python scripts/gen_api_docs.py            # (re)write docs/api.md
  python scripts/gen_api_docs.py --check    # exit 1 if docs/api.md is stale
"""

import inspect
import pathlib
import sys
import textwrap

# host-only work — must not touch a device (see scripts/docs_build.py)
import jax

jax.config.update("jax_platforms", "cpu")

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
OUT = REPO / "docs" / "api.md"

SECTIONS = [
    ("Top-level API", "batchreactor_tpu",
     ["batch_reactor", "batch_reactor_sweep", "Chemistry",
      "SensitivityProblem", "SensitivitySolution", "compile_gaschemistry",
      "compile_mech", "create_thermo", "input_data"]),
    # the intro carries the mode table — docstring first paragraphs are
    # prose-wrapped, so tables live here
    ("Non-isothermal reactors (energy equation)", "batchreactor_tpu.energy",
     ["resolve_energy", "make_energy_rhs", "make_energy_jac",
      "extend_states", "energy_cfg", "energy_atol_scale",
      "energy_ignition_observer", "extract_delay", "merge_observers",
      "interp_crossing", "grid_crossing", "temperature_ignition_qoi",
      "delay_sensitivity_forward"],
     """\
The energy subsystem (equations, T-row norm convention, ignition-delay
semantics: docs/energy.md) adds the temperature ODE behind the
``energy=`` knob of ``batch_reactor_sweep``:

| ``energy=``       | family                         | state           |
|-------------------|--------------------------------|-----------------|
| ``None`` (default)| isothermal (reference physics) | ``[rho_k]``     |
| ``"adiabatic_v"`` | adiabatic, constant volume     | ``[rho_k, T]``  |
| ``"adiabatic_p"`` | adiabatic, constant pressure   | ``[rho_k, T]``  |

``energy=None`` is a traced no-op (tier-C ``energy-noop-fork``); the
non-None modes return per-lane ``out["T"]`` / ``out["ignition_delay"]``
and weight the T row's error norm at ``atol_T`` through the reserved
``_atol_scale`` operand.
"""),
    ("Parameter sensitivities", "batchreactor_tpu.sensitivity",
     ["select", "extract", "apply", "names", "ParamSpec", "make_fdot",
      "solve_forward", "solve_adjoint", "final_species_qoi",
      "ignition_delay_qoi", "normalized_sensitivities", "top_k"]),
    ("Ensemble & distributed sweeps", "batchreactor_tpu.parallel",
     ["ensemble_solve", "ensemble_solve_forward",
      "ensemble_solve_segmented", "checkpointed_sweep",
      "temperature_sweep", "make_mesh", "pad_batch", "condition_grid",
      "premixed_mole_fracs", "sweep_solution_vectors", "ignition_observer",
      "ignition_delay", "sweep_report", "save_result", "load_result"]),
    ("Multi-host (DCN) tier", "batchreactor_tpu.parallel.multihost",
     ["initialize", "global_mesh", "scatter_batch", "gather_batch",
      "ensemble_solve_multihost", "elastic_checkpointed_sweep",
      "host_liveness"]),
    # the intro carries the knob/fault table — docstring first paragraphs
    # are prose-wrapped, so tables live here
    ("Fault tolerance", "batchreactor_tpu.resilience",
     ["run_guarded", "RetryPolicy", "QuarantinePolicy", "normalize_retry",
      "normalize_quarantine", "WedgeError", "fetch_with_deadline",
      "block_with_deadline", "resolve_fetch_deadline", "reset_backend",
      "terminate_self", "mark_suspect", "suspect_devices",
      "clear_suspects", "native_oracle"],
     """\
The resilience layer (failure model, recovery semantics and the
fault-injection harness: docs/robustness.md) turns the four postmortem
fault classes into recoverable events:

| fault            | detection                                  | recovery |
|------------------|--------------------------------------------|----------|
| wedged fetch     | watchdog deadline (`fetch_deadline=`, `chunk_budget_s=`) | `WedgeError` -> chunk `retry=` with backend reset |
| killed process   | heartbeat liveness (`elastic_checkpointed_sweep`) | survivor steals + re-solves the dead owner's chunks |
| corrupt chunk    | load validation on resume                  | file set aside as `*.corrupt`, chunk re-solved |
| failed lane      | per-lane `status` (`quarantine=`)          | same-settings retry -> tighter-tol fallback -> optional native oracle |

Every recovery path emits `fault` events and counters on the `obs`
recorder and is exercised in tier-1 by the deterministic injection hooks
in `resilience.inject` (`BR_FAULT_INJECT`)."""),
    ("Observability", "batchreactor_tpu.obs",
     ["Recorder", "CompileWatch", "build_report", "render", "diff",
      "stats_totals", "to_jsonl", "from_jsonl", "to_prometheus",
      "write_jsonl", "read_jsonl", "LiveRegistry", "MetricsServer",
      "resolve_live_metrics", "FlightRecorder", "arm_flight",
      "flight_dump"]),
    ("Live telemetry plane", "batchreactor_tpu.obs.live",
     ["write_fleet_snapshot", "read_fleet_snapshots", "merge_fleet",
      "fleet_prometheus"],
     """\
The in-flight half of the telemetry subsystem (docs/observability.md
"Live metrics" / "Fleet view" / "Flight recorder"): `MetricsServer`
serves `/metrics` + `/healthz` from a `LiveRegistry` the sweep drivers
publish into at poll boundaries (`live=` / `live_metrics=` /
`BR_METRICS_PORT`), elastic multihost processes drop per-host metric
snapshots that merge into one fleet view (counters summed, gauges
max-reduced; `scripts/obs_fleet.py`), and the armed `FlightRecorder`
dumps `flight_<ts>.jsonl` postmortems on wedges, retry exhaustion, and
SIGTERM."""),
    ("Request tracing", "batchreactor_tpu.obs.trace",
     ["RequestTrace"],
     """\
Per-request latency waterfalls (docs/observability.md "Request
tracing"): monotonic stage marks over the fixed vocabulary
`submitted -> coalesced -> admitted -> first_harvest -> resolved`
(+ `stalled` under fault injection), captured by the serving
scheduler, exported in responses behind the `trace=` request key and
as `request_trace` recorder events (`scripts/obs_trace.py` renders
the waterfalls; `scripts/obs_gate.py` band-checks the derived
`serve_stage_seconds` histograms against a banked baseline)."""),
    ("Fleet tracing", "batchreactor_tpu.obs.stitch",
     ["load_fleet", "stitch", "merge_reports", "select_traces",
      "render_fleet"],
     """\
Cross-host trace stitching (docs/observability.md "Fleet tracing"):
the router's terminal `request_trace` events carry a per-attempt hop
ledger (member tried, hop number, send/recv wall bracket, outcome)
and each member's carry the inherited `trace_ctx` identity, so one
routed request — failover chain included — stitches into ONE
clock-skew-corrected fleet waterfall (`scripts/obs_trace.py --fleet`
renders them; `merge_reports` folds the fleet's counters and
histograms into one `scripts/obs_gate.py`-checkable report)."""),
    ("SLO monitor", "batchreactor_tpu.obs.slo",
     ["Objective", "SloMonitor", "evaluate_traces"],
     """\
Continuous SLO monitoring (docs/observability.md "SLO monitor"):
declarative objectives over the routed request stream (`latency` /
`error` / `failover` budgets), sliding windows, and multi-window
burn-rate alerting — alert transitions land as `slo_alert` recorder
events, the continuous state rides the router `/metrics` as
`br_slo_*` gauges, and `scripts/obs_slo.py --gate` re-checks stitched
fleet traces against a banked `br-slo-gate-v1` baseline in CI."""),
    ("Histograms", "batchreactor_tpu.obs.counters",
     ["hist_new", "hist_observe", "hist_merge", "hist_quantile",
      "hist_mean"],
     """\
Fixed log-spaced latency histograms (docs/observability.md
"Histograms"): one shared bucket ladder (`HIST_BUCKET_EDGES`, 100 us
doubling to ~52 s + overflow) so any two histograms merge by
slot-wise sum; `Recorder.observe(name, seconds, **labels)` records,
reports carry a `histograms` section, and `obs.export` renders the
Prometheus `_bucket`/`_sum`/`_count` triple
(`br_serve_stage_seconds{stage=}`)."""),
    ("Solver timelines", "batchreactor_tpu.obs.timeline",
     ["validate", "decode", "render", "has_timeline"],
     """\
Per-lane rings of recent step-attempt records (`timeline=N` on the
solvers and sweep entry points; docs/observability.md "Solver
timelines"): `(t, h, code)` per attempt with the code packing outcome
and cause — accepted order, error reject (-1), convergence reject (-2).
Rendered by `scripts/obs_report.py --timeline`."""),
    ("Solvers", "batchreactor_tpu.solver.bdf", ["solve"]),
    ("Solvers (SDIRK)", "batchreactor_tpu.solver.sdirk", ["solve"]),
    # the intro (4th element) carries the mode table — docstring first
    # paragraphs are prose-wrapped, so tables live here
    ("Newton linear algebra", "batchreactor_tpu.solver.linalg",
     ["resolve_linsolve", "factor_m", "apply_factor", "make_solve_m"],
     """\
`linsolve=` picks how each Newton correction solves M dx = r
(M = I - cJ).  Modes (semantics: `solver/linalg.MODES`; performance:
docs/performance.md "Newton linear algebra"):

| mode      | arithmetic                              | accuracy class        | when |
|-----------|-----------------------------------------|-----------------------|------|
| `lu`      | f64 pivoted elimination (pure jnp)      | exact / golden parity | CPU default; f64 fallback everywhere |
| `inv32`   | f32 inverse + one f64 refinement pass   | ~f64 below cond 1e7   | accelerator SDIRK default |
| `inv32nr` | f32 inverse, no refinement              | f32 preconditioner    | explicit opt-in |
| `inv32f`  | f32 inverse and f32 matvec              | f32 preconditioner    | accelerator BDF default |
| `lu32p`   | Pallas-blocked batched f32 LU (pivoted) | f32 preconditioner    | TPU BDF at `B * n >= LU32P_MIN_BN` (32768) |

`"auto"` follows ONE resolution rule — `resolve_linsolve`, the
`resolve_jac_window` convention, shared by every entry point so the mode
cannot drift between them.  `lu32p` runs the hand-written kernel in
`solver/linalg_pallas.py` (`interpret=` defaults to interpreter mode
off-TPU, so CPU CI exercises the same program).  The related BDF knobs
`setup_economy=` / `stale_tol=` (CVODE msbp/dgamrat setup economy,
docs/performance.md "Newton setup economy") reuse the carried
factorization across `jac_window` boundaries until `|c/c0 - 1| >
stale_tol` (default 0.3) or a Newton convergence failure forces a
refresh."""),
    ("Mechanism-shape padding", "batchreactor_tpu.models.padding",
     ["pad_gas_mechanism", "pad_thermo", "pad_states", "nlive_cfg",
      "mech_shape_class"],
     """\
The species/reaction twin of lane-count bucketing (docs/performance.md
"Mechanism-shape economy"): pad a mechanism onto a canonical (S, R)
rung with a provably inert dead tail — zero rates, identity Newton
rows/cols, live-count error norms — so mechanisms of one size class
share compiled executables, exactly as sweep sizes share bucket
programs.  Consumed through `batch_reactor_sweep(species_buckets=,
reaction_buckets=, mech_operands=)` and the serving session spec."""),
    ("AOT program store", "batchreactor_tpu.aot",
     ["warmup", "spec_keys", "configure_cache", "program_key",
      "mechanism_fingerprint", "bundle_shape_signature",
      "normalize_buckets", "resolve_bucket", "bucket_ladder",
      "load_manifest", "merge_manifests", "touch_keys", "pin_keys",
      "enforce_capacity", "cache_stats"],
     """\
Shape-bucketed ahead-of-time compilation (docs/performance.md
"Compile economy" / "Mechanism-shape economy"): canonical (B, S, R)
program ladders, zero-span warmup through the real sweep drivers into
the persistent compilation cache, a manifest with per-program
compile/hit accounting, and — now that mechanism uploads make the
program set user-extensible — use-tracking with an LRU eviction + pin
policy (`aot_evictions` counter).  CLI: `scripts/warm_cache.py`
(`--spec`, `--fanout`, `--list`, `--evict/--pin/--unpin`)."""),
    ("Serving", "batchreactor_tpu.serving",
     ["validate_request", "validate_upload", "Request",
      "error_response", "ok_response",
      "load_spec", "SessionSpec", "SolverSession", "SessionStore",
      "UnknownMechanism", "Scheduler",
      "RequestResult", "Overloaded", "Draining", "ServingServer",
      "serve_jsonl", "SolveClient", "ServeError", "poisson_trace",
      "trace_summary"],
     """\
Sweep-as-a-service (docs/serving.md): a resident daemon answering a
live stream of `(T, p, X, t1, rtol/atol)` requests from one warm,
continuously-batched device program — warm AOT executables
(`scripts/warm_cache.py --spec serve.json`), the streaming admission
driver's live feed (`parallel/sweep.py` `_feed`/`_on_harvest`),
explicit `overloaded`/`draining` backpressure, SIGTERM graceful drain,
and the live `/metrics` plane.  The multi-mechanism store
(`SessionStore`, `POST /mechanism`, per-request `mech` routing) serves
MANY mechanisms from one daemon, sharing executables per (B, S, R)
rung under `mech_operands`.  Entry points: `scripts/serve.py`
(HTTP + stdin-JSONL, `--store`/`--add-mech`) and
`scripts/serve_bench.py` (seeded Poisson load, `--mechs` — the
round-10/11 latency/throughput evidence)."""),
    ("Fleet (replicated serving)", "batchreactor_tpu.fleet",
     ["HashRing", "canonical_key", "request_key", "DEFAULT_VNODES",
      "MemberRegistration", "MemberInfo", "read_members", "member_paths",
      "DEFAULT_HEARTBEAT_S", "DEFAULT_DEAD_AFTER_S",
      "UploadJournal", "replicate_upload", "FleetRouter"],
     """\
The replicated serving tier (docs/serving.md "Fleet"): N `serving/`
daemons behind a thin, jax-free HTTP router that consistent-hashes
each request by (mechanism fingerprint, pack key) so every member's
warmed AOT programs and resident streaming epochs stay hot.
Membership is elastic over a shared fleet dir via the
`resilience.heartbeat` mtime convention (register / beat /
drain-handshake / age-out); a member's death re-routes its arcs to
the survivors with honest retry provenance (`router.failover`,
`router.tried` — answered exactly once, never silently dropped).
`POST /mechanism` uploads replicate fleet-wide (journal-first,
idempotent by fingerprint, replayed to late joiners) and the router's
`GET /metrics` serves the merged per-host fleet families plus the
`route`/`failover`/`membership` counters and the `route_seconds`
direct|failover histogram split.  Entry points:
`scripts/serve_fleet.py` (N daemons + router under one supervisor),
`scripts/serve.py --fleet-dir` (one member), and
`scripts/serve_bench.py --router N` (per-host cond/s +
failover-latency split)."""),
    ("Static analysis (brlint)", "batchreactor_tpu.analysis",
     ["lint_paths", "lint_file", "Baseline", "Finding", "all_rules",
      "program_contract", "run_contracts", "all_contracts",
      "lint_concurrency_paths", "lint_concurrency_file",
      "Budget", "CostProbe", "check_budget", "Cost", "cost_jaxpr",
      "contract_cost_table", "estimate_rung", "fits_hbm",
      "lu32p_vmem_bytes"],
     """\
The tiered lint gate (docs/development.md): tier A is the AST
tracer-safety scan; tier C is (a) the **program-contract registry** —
every traced program registers purity/no-op-fork/kernel-presence
obligations at its definition site via `@program_contract`, one engine
(`run_contracts`) evaluates them all, and a completeness check fails
when an armed CompileWatch label has no contract — plus the
fingerprint-completeness and counter-registry audits, and (b) the
**host-concurrency lint** (`lint_concurrency_paths`) over the threaded
serving stack: lock discipline, `*_locked` call-site checking, lock
ordering, blocking-under-lock, and the PR-8 donation-aliasing rule.
Tier D is the **static cost/memory model** (`cost_jaxpr`: per-program
FLOPs, bytes moved, peak live-buffer residency, Pallas VMEM from
per-primitive jaxpr rules) with **budget contracts** — a
`@program_contract` grows an optional `budget=Budget(...)` band
evaluated by the same engine — and the stdlib closed-form
`estimate_rung`/`fits_hbm` half that powers the `scripts/brcost.py`
(B, S, R) HBM ladder and S³ sweeps with no jax at all.
CLI: `scripts/brlint.py` (`--tier C`/`--tier D`, `--contracts`,
`--budgets`, `--concurrency`) and `scripts/brcost.py` (`--table`,
`--gate`, `--ladder`, `--s-ladder`)."""),
    ("Kinetics kernels", "batchreactor_tpu.ops.rhs",
     ["make_gas_rhs", "make_gas_jac", "make_surface_rhs",
      "make_surface_jac", "make_udf_rhs"]),
    ("Native C++ runtime", "batchreactor_tpu.native",
     ["available", "gas_rhs", "solve_gas_bdf", "solve_surf_bdf"]),
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return ""


def render():
    import importlib

    lines = ["# API reference",
             "",
             "Generated from live docstrings by `scripts/gen_api_docs.py` "
             "— do not edit by hand (CI checks freshness).",
             ""]
    for title, modname, names, *intro in SECTIONS:
        mod = importlib.import_module(modname)
        lines += [f"## {title} (`{modname}`)", ""]
        if intro:
            lines += [intro[0], ""]
        for name in names:
            obj = getattr(mod, name, None)
            if obj is None:
                raise SystemExit(
                    f"{modname}.{name} listed in gen_api_docs.SECTIONS but "
                    f"missing from the package — update the section table")
            doc = inspect.getdoc(obj) or "(no docstring)"
            first_para = doc.split("\n\n")[0]
            kind = "class" if inspect.isclass(obj) else "function"
            lines += [f"### `{name}{_sig(obj)}`" if kind == "function"
                      else f"### `class {name}`",
                      "",
                      textwrap.fill(" ".join(first_para.split()), width=78),
                      ""]
    return "\n".join(lines) + "\n"


def main(argv):
    text = render()
    if "--check" in argv:
        if not OUT.exists() or OUT.read_text() != text:
            print("docs/api.md is stale; regenerate with "
                  "python scripts/gen_api_docs.py", file=sys.stderr)
            return 1
        print("docs/api.md is fresh")
        return 0
    OUT.write_text(text)
    print(f"wrote {OUT.relative_to(REPO)} ({len(text)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
