#!/usr/bin/env python
"""The replicated serving tier under one supervisor (docs/serving.md
"Fleet").

Spawns N ``scripts/serve.py`` member daemons — one serving process per
member, each warming the SHARED persistent cache dir under its own
part-manifest tag and registering into the SHARED fleet dir — then
starts the in-process :class:`fleet.FleetRouter` over them and prints
one startup JSON line with the router URL and every member's pid:

  # two members + router on an ephemeral port
  python scripts/serve_fleet.py --spec serve.json -n 2 \\
      --fleet-dir /tmp/fleet --cache-dir /tmp/brcache

  {"fleet": {"url": ..., "port": ..., "pid": ..., "members": [...]}}

Clients speak to the router exactly as they would to one daemon
(``POST /solve`` / ``POST /mechanism`` / ``GET /metrics`` /
``GET /healthz`` — ``serving.SolveClient`` works unchanged); requests
consistent-hash by (mechanism, pack key) so each member's warmed AOT
programs and resident epochs stay hot.  Kill a member (``kill -9``) and
its hash arcs reassign to the survivors: the router fails the in-flight
forwards over with retry provenance in the response's ``router`` block,
and the fleet keeps answering.

SIGTERM/SIGINT drains: members get SIGTERM (each answers its accepted
work, runs the drain handshake, deregisters), then the router stops.
A member that dies on its own does NOT take the supervisor down —
elastic membership is the point.

The supervisor itself is jax-free (the ``scripts/brlint.py`` namespace-
parent discipline): the routing plane must come up, and stay up, on a
host whose devices are wedged.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# lightweight namespace parent (scripts/brlint.py): import fleet/ (and
# the obs/serving stdlib planes it rides) WITHOUT executing
# batchreactor_tpu/__init__.py, which imports jax + the solver stack.
# setdefault: a process that already imported the real package keeps it.
_pkg = types.ModuleType("batchreactor_tpu")
_pkg.__path__ = [os.path.join(REPO, "batchreactor_tpu")]
sys.modules.setdefault("batchreactor_tpu", _pkg)


def _relay(proc, name):
    """Copy one member's stdout to our stderr, prefixed — the member's
    startup JSON and serve logs stay visible without stealing the
    supervisor's stdout (which carries OUR startup JSON line)."""

    def _pump():
        for line in proc.stdout:
            sys.stderr.write(f"[{name}] {line.decode(errors='replace')}")
            sys.stderr.flush()

    t = threading.Thread(target=_pump, daemon=True,
                         name=f"br-fleet-relay-{name}")
    t.start()
    return t


def spawn_member(args, name):
    cmd = [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
           "--spec", args.spec, "--fleet-dir", args.fleet_dir,
           "--member-name", name, "--flight-dir", args.flight_dir]
    if args.obs_dir:
        # one trace stream per host, file stem = member name — the
        # obs.stitch join convention (fleet.member_obs_path layout)
        cmd += ["--obs-out", os.path.join(args.obs_dir,
                                          f"{name}.jsonl")]
    if args.cache_dir:
        cmd += ["--cache-dir", args.cache_dir]
    if args.no_warmup:
        cmd += ["--no-warmup"]
    if args.store:
        cmd += ["--store"]
    for spec_str in args.add_mech:
        cmd += ["--add-mech", spec_str]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=sys.stderr)
    _relay(proc, name)
    return proc


def wait_routable(fleet_dir, want, procs, timeout_s, dead_after_s):
    """Block until ``want`` members are routable in ``fleet_dir`` (each
    registers only after its port is bound and its stream is live).  A
    member that exits before registering aborts the launch loudly."""
    from batchreactor_tpu.fleet import read_members

    deadline = time.monotonic() + timeout_s
    while True:
        members = [m for m in read_members(fleet_dir, dead_after_s)
                   if m.routable]
        if len(members) >= want:
            return members
        for name, proc in procs.items():
            rc = proc.poll()
            if rc is not None:
                raise SystemExit(
                    f"[serve_fleet] member {name} exited rc={rc} "
                    f"before registering — aborting launch")
        if time.monotonic() >= deadline:
            raise SystemExit(
                f"[serve_fleet] {len(members)}/{want} members routable "
                f"after {timeout_s:.0f}s — aborting launch")
        time.sleep(0.2)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spec", required=True,
                    help="session spec JSON, shared by every member")
    ap.add_argument("-n", "--members", type=int, default=2,
                    help="member daemon count (default 2)")
    ap.add_argument("--fleet-dir", required=True,
                    help="shared membership/telemetry directory")
    ap.add_argument("--cache-dir",
                    default=os.environ.get("JAX_COMPILATION_CACHE_DIR"),
                    help="shared persistent compilation cache dir "
                         "(members fold per-member part manifests)")
    ap.add_argument("--port", type=int, default=0,
                    help="router HTTP port (0 = ephemeral, printed in "
                         "the startup JSON)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--no-warmup", action="store_true",
                    help="members skip the in-process AOT warmup pass")
    ap.add_argument("--store", action="store_true",
                    help="members run the multi-mechanism store "
                         "(enables POST /mechanism replication)")
    ap.add_argument("--add-mech", action="append", default=[],
                    metavar="ID=MECH:THERM",
                    help="forwarded to every member (implies --store)")
    ap.add_argument("--flight-dir", default=".",
                    help="members' flight_*.jsonl postmortem directory")
    ap.add_argument("--obs-dir", nargs="?", const="auto", default=None,
                    metavar="DIR",
                    help="write per-host trace streams here at drain "
                         "(router.jsonl + one <member>.jsonl each — "
                         "the obs.stitch / obs_trace.py --fleet "
                         "layout); bare --obs-dir means "
                         "<fleet_dir>/obs")
    ap.add_argument("--dead-after-s", type=float, default=None,
                    help="heartbeat age past which a member is dead "
                         "(default fleet.DEFAULT_DEAD_AFTER_S)")
    ap.add_argument("--startup-timeout", type=float, default=600.0,
                    help="seconds to wait for all members to warm up "
                         "and register")
    args = ap.parse_args(argv)
    if args.add_mech:
        args.store = True

    from batchreactor_tpu.fleet import DEFAULT_DEAD_AFTER_S, FleetRouter

    dead_after_s = (DEFAULT_DEAD_AFTER_S if args.dead_after_s is None
                    else args.dead_after_s)
    os.makedirs(args.fleet_dir, exist_ok=True)
    if args.obs_dir == "auto":
        from batchreactor_tpu.fleet import obs_dir as _fleet_obs_dir

        args.obs_dir = _fleet_obs_dir(args.fleet_dir)
    elif args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)

    procs = {}
    for i in range(args.members):
        name = f"m{i + 1}"
        procs[name] = spawn_member(args, name)
        print(f"[serve_fleet] member {name} pid={procs[name].pid}",
              file=sys.stderr)

    stop = threading.Event()

    def _on_term(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    try:
        wait_routable(args.fleet_dir, args.members, procs,
                      args.startup_timeout, dead_after_s)
    except SystemExit:
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        raise

    with FleetRouter(args.fleet_dir, port=args.port, host=args.host,
                     dead_after_s=dead_after_s) as router:
        print(json.dumps({"fleet": {
            "url": router.url, "port": router.port, "pid": os.getpid(),
            "fleet_dir": args.fleet_dir, "cache_dir": args.cache_dir,
            "members": [{"name": name, "pid": proc.pid}
                        for name, proc in procs.items()]}}),
              flush=True)
        stop.wait()
        print("[serve_fleet] drain requested; terminating members",
              file=sys.stderr)
        # members first (each drains its accepted work under SIGTERM),
        # router second — a request arriving mid-drain fails over until
        # the last member flags draining, then answers 503/internal
        # honestly rather than hanging on a dead connection
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        for name, proc in procs.items():
            try:
                rc = proc.wait(timeout=60)
                print(f"[serve_fleet] member {name} exited rc={rc}",
                      file=sys.stderr)
            except subprocess.TimeoutExpired:
                print(f"[serve_fleet] member {name} drain timed out; "
                      f"killing", file=sys.stderr)
                proc.kill()
        if args.obs_dir:
            # the router's half of the stitched story: its hop ledgers
            # + route_seconds histograms, written AFTER the members so
            # every member's stream is already on disk (obs.stitch
            # reads the whole directory; jax-free — obs.report is
            # numpy/stdlib)
            from batchreactor_tpu.obs import build_report, write_jsonl

            path = os.path.join(args.obs_dir, "router.jsonl")
            write_jsonl(path, build_report(
                recorder=router.recorder,
                meta={"entry": "fleet-router",
                      "fleet_dir": args.fleet_dir}))
            print(f"[serve_fleet] router obs report -> {path}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
