"""Matched single-core CPU baseline for the north-star 4096-lane map.

Round-3 verdict: the "≥50× on the 4096-condition sweep" claim divided the
*bench-workload* rung by a *bench-workload* scipy baseline (0.931 s/lane);
the map's own single-core s/lane was never measured.  This script closes
that gap: it samples the 64×64 T×phi map on a stratified n×n sub-lattice
(unbiased for the uniform grid), solves each sampled condition one-at-a-time
on the CPU exactly the way the reference runs (one serial CVODE-class BDF
call per condition, /root/reference/src/BatchReactor.jl:210), and
extrapolates mean s/lane × 4096 to the full-map single-core wall-clock.

Two baseline solvers, reported separately:
- ``scipy``  — solve_ivp(method="BDF") driving the jitted-on-CPU JAX RHS
  with the ANALYTIC Jacobian supplied (stronger than the round-2 bench
  baseline, which let scipy finite-difference J — supplying J is the fair
  single-core analog of CVODE's user-Jacobian mode);
- ``native`` — the repo's independent C++ variable-order BDF runtime
  (batchreactor_tpu/native/br_native.cpp), analytic Jacobian in C++, genuinely
  single-threaded — the strongest CVODE-class single-core baseline we have.

Writes NORTHSTAR_BASELINE.json with per-solver s/lane stats and the implied
full-map speedup for the TPU number in NORTHSTAR_TPU.json (if present).

Usage:
  python scripts/northstar_baseline.py            # 8x8 = 64 sample lanes
  NB_N=4 python scripts/northstar_baseline.py     # 4x4 quick pass
  NB_SOLVERS=native python scripts/northstar_baseline.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LIB = os.environ.get("BR_LIB", "/root/reference/test/lib")
if not os.path.isdir(LIB):
    LIB = os.path.join(REPO, "tests", "fixtures")

# the north-star map definition (scripts/northstar_sweep.py run_sweep
# defaults) — keep in sync
N_FULL = 64
T_LO, T_HI = 1500.0, 2000.0
PHI_LO, PHI_HI = 0.6, 1.6
T1, P = 8e-4, 1e5
RTOL, ATOL = 1e-6, 1e-10


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    import batchreactor_tpu as br
    from batchreactor_tpu.ops.rhs import make_gas_jac, make_gas_rhs
    from batchreactor_tpu.parallel.grid import premixed_mole_fracs
    from batchreactor_tpu.utils.composition import density, mole_to_mass

    n = int(os.environ.get("NB_N", "8"))
    solvers = os.environ.get("NB_SOLVERS", "scipy,native").split(",")
    log = lambda m: print(m, file=sys.stderr, flush=True)

    gm = br.compile_gaschemistry(f"{LIB}/grimech.dat")
    th = br.create_thermo(list(gm.species), f"{LIB}/therm.dat")
    sp = list(gm.species)

    # stratified sub-lattice: centers of n×n equal blocks of the 64×64 grid
    full_T = np.linspace(T_LO, T_HI, N_FULL)
    full_phi = np.linspace(PHI_LO, PHI_HI, N_FULL)
    pick = (N_FULL // (2 * n) + (N_FULL // n) * np.arange(n))
    Ts, phis = full_T[pick], full_phi[pick]
    lanes = [(T, phi) for T in Ts for phi in phis]
    log(f"[baseline] {len(lanes)} sample lanes from the {N_FULL}x{N_FULL} "
        f"map (T {Ts[0]:.0f}..{Ts[-1]:.0f}, phi {phis[0]:.2f}.."
        f"{phis[-1]:.2f}), t1={T1}, rtol={RTOL}/atol={ATOL}")

    rhs = jax.jit(make_gas_rhs(gm, th))
    jacf = jax.jit(make_gas_jac(gm, th))

    def y0_of(T, phi):
        X = premixed_mole_fracs(sp, "CH4", jnp.asarray([phi]), stoich_o2=2.0,
                                diluent="N2", o2_to_diluent=0.5)[0]
        rho = float(density(X, th.molwt, float(T), P))
        return np.asarray(mole_to_mass(X, th.molwt)) * rho

    results = {}
    per_lane = [{"T": float(T), "phi": float(phi)} for T, phi in lanes]

    if "scipy" in solvers:
        from scipy.integrate import solve_ivp

        walls, fails = [], 0
        for i, (T, phi) in enumerate(lanes):
            y0 = y0_of(T, phi)
            cfg = {"T": jnp.asarray(float(T))}
            f = lambda t, y: np.asarray(rhs(t, jnp.asarray(y), cfg))
            J = lambda t, y: np.asarray(jacf(t, jnp.asarray(y), cfg))
            f(0.0, y0), J(0.0, y0)  # compile outside the timer
            t0 = time.perf_counter()
            sol = solve_ivp(f, (0.0, T1), y0, method="BDF",
                            rtol=RTOL, atol=ATOL, jac=J)
            walls.append(time.perf_counter() - t0)
            per_lane[i]["scipy_s"] = round(walls[-1], 4)
            fails += not sol.success
            if i % n == 0:
                log(f"[scipy] lane {i}/{len(lanes)} T={T:.0f} "
                    f"phi={phi:.2f}: {walls[-1]:.2f}s")
        results["scipy"] = {"s_per_lane_mean": float(np.mean(walls)),
                            "s_per_lane_min": float(np.min(walls)),
                            "s_per_lane_max": float(np.max(walls)),
                            "s_per_lane_std": float(np.std(walls)),
                            "n_failed": fails}

    if "native" in solvers:
        from batchreactor_tpu import native

        walls, fails = [], 0
        for i, (T, phi) in enumerate(lanes):
            y0 = y0_of(T, phi)
            t0 = time.perf_counter()
            r = native.solve_gas_bdf(gm, th, float(T), y0, 0.0, T1,
                                     rtol=RTOL, atol=ATOL, n_save=0)
            walls.append(time.perf_counter() - t0)
            per_lane[i]["native_s"] = round(walls[-1], 5)
            fails += r.status != "Success"
            if i % n == 0:
                log(f"[native] lane {i}/{len(lanes)} T={T:.0f} "
                    f"phi={phi:.2f}: {walls[-1]:.3f}s")
        results["native"] = {"s_per_lane_mean": float(np.mean(walls)),
                             "s_per_lane_min": float(np.min(walls)),
                             "s_per_lane_max": float(np.max(walls)),
                             "s_per_lane_std": float(np.std(walls)),
                             "n_failed": fails}

    B_full = N_FULL * N_FULL
    rec = {
        "workload": f"GRI30 {N_FULL}x{N_FULL} TxPhi ignition map "
                    f"(northstar_sweep.py definition), single-core CPU, "
                    f"one serial BDF call per condition",
        "sample": f"stratified {n}x{n} block-center sub-lattice "
                  f"({len(lanes)} lanes)",
        "t1": T1, "rtol": RTOL, "atol": ATOL,
        "solvers": results,
        # per-lane (T, phi, s) records feed the lane-cost model that sorts
        # the TPU map into cost-homogeneous chunks (northstar_sweep.py)
        "per_lane": per_lane,
    }
    for name, r in results.items():
        rec[f"extrapolated_full_map_wall_s_{name}"] = round(
            r["s_per_lane_mean"] * B_full, 1)

    ns_path = os.path.join(REPO, "NORTHSTAR_TPU.json")
    if os.path.exists(ns_path):
        with open(ns_path) as fh:
            ns = json.load(fh)
        tpu_wall = ns.get("wall_s")
        if tpu_wall:
            rec["tpu_wall_s"] = tpu_wall
            for name, r in results.items():
                rec[f"map_speedup_vs_{name}"] = round(
                    r["s_per_lane_mean"] * B_full / tpu_wall, 1)

    out = os.environ.get("NB_OUT", os.path.join(REPO,
                                                "NORTHSTAR_BASELINE.json"))
    with open(out, "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
