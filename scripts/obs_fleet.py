#!/usr/bin/env python
"""Merged fleet telemetry view over a shared checkpoint directory.

Each ``elastic_checkpointed_sweep`` process drops periodic metric
snapshots beside its heartbeat (``<ckpt_dir>/hosts/p<id>.metrics.json``
— ``obs.live.write_fleet_snapshot``); this CLI reads them all and
renders the pod-level picture: per-host counters/gauges, snapshot ages
(a stale snapshot = a slow, dead, or partitioned host), and the merged
reduction (counters summed, gauges max-reduced — docs/observability.md
"Fleet view").

  # human-readable table
  python scripts/obs_fleet.py /path/to/ckpt_dir

  # Prometheus text exposition (what /metrics appends with fleet_dir=)
  python scripts/obs_fleet.py /path/to/ckpt_dir --prom

  # serve the merged view on a port (standalone fleet endpoint — no
  # sweep process needed; re-reads the snapshots on every scrape)
  python scripts/obs_fleet.py /path/to/ckpt_dir --serve 9109

jax-free by design: reading JSON snapshots must work on a host whose
devices are wedged.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def render_fleet(snaps):
    from batchreactor_tpu.obs.live import merge_fleet

    merged = merge_fleet(snaps)
    lines = [f"fleet: {merged['hosts']} host(s) with snapshots"]
    now = time.time()
    for s in snaps:
        age = now - float(s.get("time", 0))
        lines.append(f"  p{s.get('pid', '?')}: snapshot age {age:.1f}s")
        for k, v in sorted((s.get("gauges") or {}).items()):
            lines.append(f"    gauge {k}: {v}")
        for k, v in sorted((s.get("counters") or {}).items()):
            lines.append(f"    counter {k}: {v}")
    lines.append("merged (counters summed, gauges max-reduced):")
    for k, v in sorted(merged["counters"].items()):
        lines.append(f"  counter {k}: {v}")
    for k, v in sorted(merged["gauges"].items()):
        lines.append(f"  gauge {k}: {v}")
    from batchreactor_tpu.obs.counters import occupancy

    occ = occupancy(merged["counters"])
    if occ is not None:
        lines.append(f"  occupancy: {occ:.4f} (fleet-wide)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merged fleet telemetry over a shared checkpoint dir")
    ap.add_argument("ckpt_dir", help="the elastic sweep's shared "
                                     "checkpoint directory")
    ap.add_argument("--prom", action="store_true",
                    help="print the Prometheus fleet exposition")
    ap.add_argument("--json", action="store_true",
                    help="print the merged reduction as JSON")
    ap.add_argument("--serve", type=int, metavar="PORT",
                    help="serve /metrics (fleet view) + /healthz on PORT "
                         "until interrupted (0 = ephemeral)")
    args = ap.parse_args(argv)

    from batchreactor_tpu.obs.live import (LiveRegistry, MetricsServer,
                                           fleet_prometheus, merge_fleet,
                                           read_fleet_snapshots)

    if args.serve is not None:
        # a registry with no recorder: /metrics is the fleet section
        # (re-read per scrape) plus the uptime gauge
        reg = LiveRegistry(meta={"entry": "obs_fleet"},
                           fleet_dir=args.ckpt_dir)
        with MetricsServer(reg, port=args.serve) as srv:
            print(f"serving fleet view of {args.ckpt_dir} on {srv.url} "
                  f"(ctrl-C to stop)", file=sys.stderr)
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                return 0

    snaps = read_fleet_snapshots(args.ckpt_dir)
    if not snaps:
        print(f"no metric snapshots under {args.ckpt_dir}/hosts "
              f"(is an elastic sweep with a recorder running?)",
              file=sys.stderr)
        return 1
    if args.prom:
        sys.stdout.write(fleet_prometheus(snaps))
    elif args.json:
        print(json.dumps(merge_fleet(snaps), indent=1, sort_keys=True))
    else:
        print(render_fleet(snaps))
    return 0


if __name__ == "__main__":
    sys.exit(main())
