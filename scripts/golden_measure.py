"""Measure parity-mode error vs golden over all 1919 rows (native backend)."""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import batchreactor_tpu as br

GOLD = "/root/reference/test/batch_gas_and_surf"
t0 = time.time()
ret = br.batch_reactor("/tmp/golden_run/batch.xml", "/root/reference/test/lib",
                       gaschem=True, surfchem=True, kc_compat=True,
                       backend="cpu")
print("retcode:", ret, f"{time.time()-t0:.1f}s")

def load(p):
    hdr = open(p).readline().strip().split(",")
    return hdr, np.loadtxt(p, delimiter=",", skiprows=1)

gh, gold = load(f"{GOLD}/gas_profile.csv")
oh, ours = load("/tmp/golden_run/gas_profile.csv")
assert gh == oh
print(f"golden rows {len(gold)}, ours {len(ours)}")
tg = gold[:, 0]
for name in ["CH4", "O2", "H2O", "CO2", "CO", "H2", "N2", "C2H6", "OH", "p", "rho"]:
    i = gh.index(name)
    oi = np.interp(tg, ours[:, 0], ours[:, i])
    d = np.abs(oi - gold[:, i])
    peak = np.abs(gold[:, i]).max()
    mask = np.abs(gold[:, i]) > 1e-3 * max(peak, 1e-30)
    rel = (d[mask] / np.abs(gold[mask, i])).max() if mask.any() else 0.0
    print(f"{name:>5}: peak {peak:.3e}  max_abs {d.max():.3e} "
          f" max_rel(>1e-3peak) {rel:.3e}")
# ignition time: CH4 half-consumption crossing
ich4 = gh.index("CH4")
def cross(t, x):
    j = np.argmax(x < 0.125)
    return t[j]
print(f"CH4-half time: gold {cross(tg, gold[:, ich4]):.5e} "
      f"ours {cross(ours[:, 0], ours[:, ich4]):.5e}")
ch, covg = load(f"{GOLD}/surface_covg.csv")
co, covo = load("/tmp/golden_run/surface_covg.csv")
assert ch == co
tgc = covg[:, 0]
worst = 0.0
for i, name in enumerate(ch[2:], start=2):
    oi = np.interp(tgc, covo[:, 0], covo[:, i])
    d = np.abs(oi - covg[:, i]).max()
    worst = max(worst, d)
    if d > 1e-3:
        print(f"covg {name}: max_abs {d:.3e} (peak {np.abs(covg[:, i]).max():.3e})")
print(f"worst coverage abs err: {worst:.3e}")
